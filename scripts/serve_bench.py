"""Serving benchmark: steady-state decode tokens/s through the
InferenceEngine (KV cache + Pallas decode kernel), plus the
continuous-batching mode (SERVE_MODE=cb) comparing the
`deepspeed_tpu/serving/` scheduler against the static-batch baseline on
a mixed-length workload.

On-chip queue item (PERF.md): MoE int8-KV serving rate, plus rates for
the new serving families (NeoX/GPT-J/BLOOM/GPT-Neo).

    python scripts/serve_bench.py                          # gpt2 125m
    SERVE_MODEL=mixtral:1b-moe SERVE_KV=int8 python scripts/serve_bench.py
    SERVE_MODEL=bloom:560m SERVE_B=8 python scripts/serve_bench.py
    SERVE_MODE=cb SERVE_REQS=16 python scripts/serve_bench.py
    SERVE_MODE=spec SERVE_REQS=16 python scripts/serve_bench.py
    SERVE_MODE=prefix SERVE_REQS=24 python scripts/serve_bench.py
    SERVE_MODE=tier SERVE_REQS=16 python scripts/serve_bench.py
    SERVE_MODE=lora SERVE_TENANTS=4 python scripts/serve_bench.py
    SERVE_MODE=moe python scripts/serve_bench.py            # mixtral A/B
    SERVE_MODE=moe SERVE_INT8_WEIGHTS=1 python scripts/serve_bench.py
    SERVE_MODE=slo SERVE_LONG_LEN=8192 python scripts/serve_bench.py
    SERVE_MODE=fleet SERVE_REPLICAS=2 python scripts/serve_bench.py
    SERVE_MODE=fused python scripts/serve_bench.py   # megakernel A/B
    SERVE_MODE=cb python scripts/serve_bench.py --json out.json

``--json out.json`` (ISSUE 7 satellite) additionally writes the result
record to a file — the machine-readable form ``scripts/
bench_compare.py`` diffs across rounds, so the bench trajectory stops
being prose-only in PERF.md.

Static mode prints one JSON line: prefill ms + steady decode tokens/s.
CB mode prints one JSON line: continuous-batching vs static-batch tok/s
on the same mixed-length workload + p50/p99 TTFT.
Spec mode (ISSUE 5) runs the ngram-proposer speculative path vs plain cb
on a mixed-length repetitive-suffix workload and reports tokens per
weight pass + acceptance rate (the ISSUE 5 acceptance columns).
Prefix mode (ISSUE 6) runs the cb scheduler on a SHARED-PREFIX workload
(N requests over M shared system prompts + distinct tails) with the
prefix cache on vs off and reports TTFT p50/p99, cache hit rate,
prefill tokens computed, and serving_goodput — the ISSUE 6 acceptance
columns (identical outputs asserted between the two runs).
MoE mode (ISSUE 8) runs a Mixtral cb workload with grouped (megablocks
ragged-GEMM) vs einsum (GShard capacity) expert dispatch — token-
identical greedy outputs asserted — and, with SERVE_INT8_WEIGHTS=1,
reports the ``weights_floor_moe`` accounting (dense int8 bytes + top-k-
distinct-expert bytes per decode step — the floor the grouped int8
path streams at; the einsum path streams ALL E experts).
SLO mode (ISSUE 9) runs the ADVERSARIAL heavy-prefill workload: a
steady pool of short chat streams decoding while a few long prompts
arrive mid-flight (step-scheduled, identical in both runs), A/B'd with
chunked prefill ON vs OFF — token-identical greedy outputs asserted —
reporting p50/p99 TPOT and TTFT per SLO class.  The acceptance shape:
with chunking OFF the chat class's p99 TPOT spikes at each long-prompt
arrival (the whole prefill runs in one scheduler iteration); with
chunking ON it stays bounded near p50.
Tier mode (ISSUE 16) runs a shared-prefix workload under a deliberately
small hot cache (LRU pressure demotes released prefixes HBM→host→NVMe)
with tiered KV ON vs OFF — token-identical greedy outputs asserted —
and reports prefill tokens saved by cold-tier swap-ins vs the
evict-and-re-prefill baseline, per-tier hit counts, and the
swap/achieved_vs_floor bandwidth rows when DS_NVME_GBPS is declared.
Fleet mode (ISSUE 11) routes a shared-prefix workload across N replica
schedulers (each with its own prefix cache) through the fleet Router,
A/B'ing the prefix-aware scored policy vs round-robin — token-identical
outputs asserted — and reports the aggregate prefix-cache hit rate per
policy (the acceptance column: scored routing concentrates same-prefix
traffic on the replica that already holds it, round-robin scatters it).
Off-TPU this still runs (tiny default shapes) as a plumbing smoke.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

# value-fetch sync (block_until_ready does not sync on the axon tunnel)
from scripts.bench_util import fetch


def emit(result: dict, json_path=None) -> dict:
    """Print the one-line JSON record (the existing convention),
    persist it with --json for bench_compare.py, and — when
    DS_BENCH_LEDGER is armed — append it (BenchRecord meta envelope
    attached) to the BENCH/ ledger history (ISSUE 13).  Every record
    gains the memory observatory's ``mem_peak_*`` watermarks
    (ISSUE 14) and the communication observatory's ``comm_*``
    per-axis wire bytes / achieved GB/s (ISSUE 19) INSIDE ``detail``
    — that is the half of a record ``bench_compare`` lifts into
    comparable metrics, so the history gates memory and interconnect
    regressions like latency ones."""
    from scripts.bench_util import comm_fields, mem_peak_fields
    detail = result.setdefault("detail", {})
    if isinstance(detail, dict):
        for k, v in mem_peak_fields().items():
            detail.setdefault(k, v)
        for k, v in comm_fields().items():
            detail.setdefault(k, v)
    print(json.dumps(result))
    if json_path:
        with open(json_path, "w") as f:
            json.dump(result, f, indent=2)
        print(f"# wrote {json_path}", file=sys.stderr)
    from scripts.bench_util import emit_ledger
    emit_ledger(result)
    return result


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="serve_bench",
        description="serving benchmark (workload shape via SERVE_* env "
                    "vars — see module docstring)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the result record to PATH "
                        "(bench_compare.py input)")
    args = p.parse_args(argv)
    json_path = args.json
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    if os.environ.get("SERVE_MODE") == "moe":
        # the dispatch A/B needs a routed-expert model
        default_model = "mixtral:1b-moe" if on_tpu else "mixtral:tiny"
    else:
        default_model = "gpt2:125m" if on_tpu else "gpt2:custom"
    spec = os.environ.get("SERVE_MODEL", default_model)
    B = int(os.environ.get("SERVE_B", 4))
    prompt_len = int(os.environ.get("SERVE_PROMPT", 128 if on_tpu else 8))
    new_tokens = int(os.environ.get("SERVE_TOKENS", 256 if on_tpu else 8))
    kv_dtype = os.environ.get("SERVE_KV") or None
    quant = bool(int(os.environ.get("SERVE_INT8_WEIGHTS", "0")))
    # int8-qgemm mode (default on): SERVE_QGEMM=0 falls back to the
    # layer-granularity maybe_stream dequant + scan-threshold defense —
    # the A/B pair for the fused-dequant kernel rows in PERF.md
    if "SERVE_QGEMM" in os.environ:
        os.environ["DS_QGEMM"] = os.environ["SERVE_QGEMM"]

    from deepspeed_tpu import models as M

    def _opt_model(size, **kw):
        # OPT serves through the gpt2-family scaffold (pre-LN + ReLU —
        # what opt_from_hf converts onto); this is the native-arch
        # equivalent for rate measurement
        return M.gpt2_model(size, activation="relu", **kw)

    def _internlm_model(size, **kw):
        # InternLM = llama block + biased q/k/v/o (llama_from_hf alias);
        # "1b" picks InternLM-1.8B-like dims (no in-tree llama preset
        # at this scale)
        if size in ("1b", ""):
            kw = dict(num_layers=16, num_heads=16, num_kv_heads=16,
                      d_model=2048, d_mlp=5504, vocab_size=50000, **kw)
            size = "custom"
        return M.llama_model(size, attn_bias=True, **kw)

    arch, _, size = spec.partition(":")
    registry = {"gpt2": M.gpt2_model, "llama": M.llama_model,
                "mixtral": M.mixtral_model, "neox": M.neox_model,
                "bloom": M.bloom_model, "gptneo": M.gptneo_model,
                "opt": _opt_model, "megatron": M.gpt2_model,
                "internlm": _internlm_model}
    if on_tpu:
        kwargs = {}
    elif arch in ("llama", "mixtral", "internlm"):
        # these archs have their own tiny presets with consistent
        # kv-heads/ffn dims — the generic tiny kwargs would not apply
        size = size or "tiny"
        kwargs = {}
    elif os.environ.get("SERVE_MODE") in ("cb", "spec", "prefix", "moe",
                                          "slo", "fleet", "fused",
                                          "tier", "lora"):
        # cb vs static is a scheduling comparison: a 2-layer d=32 toy is
        # ALL dispatch overhead and measures nothing — use the smallest
        # shape where device compute is non-trivial
        kwargs = dict(vocab_size=1024, num_layers=4, num_heads=4,
                      d_model=128)
    else:
        kwargs = dict(vocab_size=256, num_layers=2, num_heads=4,
                      d_model=32)
    # cb/spec modes size their own workloads (spec's motif-tiled prompts
    # run a little longer than cb's heavy tail off-TPU)
    _mode = os.environ.get("SERVE_MODE")
    if _mode not in ("cb", "spec", "prefix", "moe", "slo", "fleet",
                     "fused", "tier", "lora"):
        cb_ctx = 0
    elif _mode == "slo":
        # headroom for the adversarial long prompts (heavy-prefill
        # overload is the whole point of this mode)
        cb_ctx = int(os.environ.get(
            "SERVE_LONG_LEN", 8192 if on_tpu else 640)) + 256
    elif on_tpu:
        cb_ctx = 768 + 384
    elif _mode in ("prefix", "fleet"):
        # headroom for the shared system prompts — the long-shared-head
        # short-tail regime is the whole point of these modes
        cb_ctx = int(os.environ.get("SERVE_SYS_LEN", 512)) + 128
    elif _mode == "tier":
        # same shared-head regime, but the CPU smoke keeps the heads
        # short: the point is demote/swap-in plumbing, not prefill mass
        cb_ctx = int(os.environ.get("SERVE_SYS_LEN",
                                    512 if on_tpu else 64)) + 128
    else:
        cb_ctx = 96 if _mode in ("cb", "moe") else 128
    model = registry[arch](size or "custom", dtype="bfloat16" if on_tpu
                           else "float32",
                           max_seq_len=max(2048 if on_tpu else 64,
                                           prompt_len + new_tokens, cb_ctx),
                           **kwargs)

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    cfg = DeepSpeedInferenceConfig(
        dtype="bfloat16" if on_tpu else "float32",
        quant={"enabled": quant},
        kv_cache_dtype=kv_dtype)
    params = None
    n_params = model.meta.get("n_params", 0)
    if quant and n_params * 2 > 8e9 and model.numpy_init_fn is not None:
        # int8 serving of models beyond HBM at full precision (the MoQ
        # big-model path): init on HOST, quantize leaf-by-leaf on device
        # — device-side init would materialize the full bf16 tree first
        print(f"# host-init {n_params/1e9:.1f}B params for int8 serving",
              file=sys.stderr)
        params = model.numpy_init_fn(seed=0)
    eng = InferenceEngine(model, cfg, model_parameters=params)

    if os.environ.get("SERVE_MODE") == "cb":
        return bench_continuous_batching(model, eng, spec, kv_dtype, on_tpu,
                                         json_path)
    if os.environ.get("SERVE_MODE") == "spec":
        return bench_spec_decoding(model, eng, spec, kv_dtype, on_tpu,
                                   json_path)
    if os.environ.get("SERVE_MODE") == "prefix":
        return bench_prefix_cache(model, eng, spec, kv_dtype, on_tpu,
                                  json_path)
    if os.environ.get("SERVE_MODE") == "tier":
        return bench_kv_tiering(model, eng, spec, kv_dtype, on_tpu,
                                json_path)
    if os.environ.get("SERVE_MODE") == "lora":
        return bench_lora_multitenant(model, eng, spec, kv_dtype, on_tpu,
                                      json_path)
    if os.environ.get("SERVE_MODE") == "moe":
        return bench_moe_dispatch(model, eng, spec, kv_dtype, quant,
                                  on_tpu, json_path)
    if os.environ.get("SERVE_MODE") == "slo":
        return bench_slo_chunked(model, eng, spec, kv_dtype, on_tpu,
                                 json_path)
    if os.environ.get("SERVE_MODE") == "fleet":
        return bench_fleet_routing(model, eng, spec, kv_dtype, on_tpu,
                                   json_path)
    if os.environ.get("SERVE_MODE") == "fused":
        return bench_fused_ab(model, eng, spec, kv_dtype, on_tpu,
                              json_path)

    rng = np.random.default_rng(0)
    prompts = rng.integers(1, model.config.vocab_size,
                           (B, prompt_len)).astype(np.int32)
    # decode rate = SLOPE between two generate lengths (min over repeats):
    # a one-shot (full - prefill) difference carries the axon tunnel's
    # ~100 ms fixed round-trip jitter twice and swings +-20% run to run;
    # the slope between two lengths measured min-of-3 cancels prefill and
    # every fixed cost
    small = max(1, new_tokens // 4)

    def timed(n, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            fetch(eng.generate(prompts, max_new_tokens=n,
                                    do_sample=False))
            best = min(best, time.time() - t0)
        return best

    # warmup/compile all program shapes
    fetch(eng.generate(prompts, max_new_tokens=1, do_sample=False))
    fetch(eng.generate(prompts, max_new_tokens=small, do_sample=False))
    fetch(eng.generate(prompts, max_new_tokens=new_tokens,
                            do_sample=False))
    t_prefill = timed(1)
    t_small = timed(small)
    t_full = timed(new_tokens)
    decode_s = t_full - t_small
    toks = B * (new_tokens - small)
    if decode_s <= 0:
        # timing noise swamped the marginal decode time (tiny smoke
        # shapes) — emit null rather than a garbage rate
        rate = None
    else:
        rate = round(toks / decode_s, 1)
    from deepspeed_tpu.models.serving import qgemm_enabled
    emit({
        "metric": f"{spec}_serve"
                  + ("_int8kv" if kv_dtype == "int8" else "")
                  + (("_int8w_qgemm" if qgemm_enabled() else "_int8w_dq")
                     if quant else ""),
        "value": rate,
        "unit": "decode_tokens_per_sec",
        "detail": {"batch": B, "prompt_len": prompt_len,
                   "new_tokens": new_tokens,
                   "prefill_ms": round(t_prefill * 1e3, 2),
                   "total_s": round(t_full, 3)},
    }, json_path)


def bench_fused_ab(model, eng, spec, kv_dtype, on_tpu, json_path=None):
    """Fused-megakernel on/off A/B through the cb scheduler (ISSUE 12):
    the same mixed-length greedy workload twice — fused off (per-op
    composition) vs on (``ds_fused_layer`` per layer) — with
    token-identical outputs ASSERTED, so the A/B isolates launches and
    scaffolding.  Off-TPU the fused path runs the jnp reference
    composition (structural A/B only, no launch win — the CPU-crossover
    caveat in docs/tutorials/serving.md); the on-chip rows are queued in
    PERF.md.  ``--json`` emits both rows for bench_compare gating."""
    import time as _time
    from deepspeed_tpu.ops.pallas.fused_decode import fused_decode_scope
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    n_reqs = int(os.environ.get("SERVE_REQS", 16 if on_tpu else 8))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    p_lo, p_hi = ((32, 512) if on_tpu else (4, 24))
    n_lo, n_hi = ((8, 128) if on_tpu else (2, 12))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    workload = [
        (rng.integers(1, V, (int(pl),)).astype(np.int32), int(nn))
        for pl, nn in zip(rng.integers(p_lo, p_hi, n_reqs),
                          rng.integers(n_lo, n_hi, n_reqs))]
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 4
    need = -(-max_len // bs) + 1
    cfg = ServingConfig(block_size=bs, max_num_seqs=max_seqs,
                        num_blocks=1 + need * max_seqs,
                        max_num_batched_tokens=1 << 30)

    def run(fused):
        with fused_decode_scope(fused):
            sched = ContinuousBatchingScheduler(
                model, eng.params, cfg, kv_cache_dtype=kv_dtype)

            def once():
                t0 = _time.time()
                reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn))
                        for p, nn in workload]
                sched.run_until_idle()
                return (_time.time() - t0,
                        [np.asarray(r.output_ids) for r in reqs])

            once()                          # compile warm
            best, outs = min((once() for _ in range(2)),
                             key=lambda r: r[0])
        return best, outs

    off_s, off_out = run(False)
    on_s, on_out = run(True)
    for a, b in zip(off_out, on_out):       # the A/B contract
        np.testing.assert_array_equal(a, b)
    return emit({
        "bench": "serve_fused_ab", "model": spec,
        "kv": kv_dtype or "native", "device": jax.devices()[0].device_kind,
        "requests": n_reqs, "useful_tokens": useful,
        "token_identical": True,
        "unfused": {"wall_s": round(off_s, 3),
                    "tok_s": round(useful / off_s, 1)},
        "fused": {"wall_s": round(on_s, 3),
                  "tok_s": round(useful / on_s, 1)},
        "fused_speedup": round(off_s / on_s, 3),
    }, json_path)


def bench_continuous_batching(model, eng, spec, kv_dtype, on_tpu,
                              json_path=None):
    """Mixed-length workload through the iteration-level scheduler vs the
    static-batch baseline (rectangular pad, batch drains as a unit).

    The static baseline processes the same requests in arrival order in
    batches of ``max_num_seqs``, padded to the batch max prompt and
    decoding the batch max new_tokens — what `generate` alone offers.
    Useful tokens (each request's own max_new_tokens) over wall time."""
    import time as _time
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    n_reqs = int(os.environ.get("SERVE_REQS", 32 if on_tpu else 16))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    # heavy-tailed lengths — the regime continuous batching exists for
    # (a static batch pads every row to the batch max in BOTH dims)
    p_lo, p_hi = ((32, 768) if on_tpu else (4, 48))
    n_lo, n_hi = ((8, 384) if on_tpu else (2, 48))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    workload = [
        (rng.integers(1, V, (int(pl),)).astype(np.int32), int(nn))
        for pl, nn in zip(rng.integers(p_lo, p_hi, n_reqs),
                          rng.integers(n_lo, n_hi, n_reqs))]
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 4
    need = -(-(max_len) // bs) + 1
    cfg = ServingConfig(
        block_size=bs, max_num_seqs=max_seqs,
        num_blocks=1 + need * max_seqs,     # full batch fits: measures
        max_num_batched_tokens=1 << 30)     # scheduling, not preemption

    sched = ContinuousBatchingScheduler(
        model, eng.params, cfg, kv_cache_dtype=kv_dtype)

    def run_cb():
        # one scheduler across warmup+measurement: its jitted step fns
        # (and their compiles) persist, as in a long-lived server
        t0 = _time.time()
        reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn))
                for p, nn in workload]
        sched.run_until_idle()
        dt = _time.time() - t0
        assert all(len(r.output_ids) == nn
                   for r, (_, nn) in zip(reqs, workload))
        ttfts = sorted(r.ttft_s for r in reqs)
        return dt, ttfts

    def run_static():
        t0 = _time.time()
        ttfts = []
        for i in range(0, n_reqs, max_seqs):
            batch = workload[i:i + max_seqs]
            plen = max(p.size for p, _ in batch)
            new = max(nn for _, nn in batch)
            toks = np.zeros((len(batch), plen), np.int32)
            for j, (p, _) in enumerate(batch):
                toks[j, :p.size] = p        # right-padded rectangle
            t_b = _time.time()
            fetch(eng.generate(toks, max_new_tokens=new,
                                    do_sample=False))
            # static batches emit every token before ANY request returns:
            # TTFT = the whole batch latency, for every request in it
            ttfts.extend([_time.time() - t_b] * len(batch))
        return _time.time() - t0, sorted(ttfts)

    # warm both paths' compiles out of the measurement; then min-of-3
    # (same convention as the static-mode slope measurement)
    run_cb()
    run_static()
    cb_s, cb_ttft = min((run_cb() for _ in range(3)),
                        key=lambda r: r[0])
    st_s, st_ttft = min((run_static() for _ in range(3)),
                        key=lambda r: r[0])
    pct = lambda xs, q: round(float(np.percentile(xs, q)) * 1e3, 2)
    emit({
        "metric": f"{spec}_serve_cb"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": round(useful / cb_s, 1),
        "unit": "tokens_per_sec",
        "detail": {
            "requests": n_reqs, "useful_tokens": useful,
            "max_num_seqs": max_seqs, "block_size": bs,
            "cb_tok_s": round(useful / cb_s, 1),
            "static_tok_s": round(useful / st_s, 1),
            "speedup_vs_static": round(st_s / cb_s, 3),
            "cb_ttft_p50_ms": pct(cb_ttft, 50),
            "cb_ttft_p99_ms": pct(cb_ttft, 99),
            "static_ttft_p50_ms": pct(st_ttft, 50),
            "static_ttft_p99_ms": pct(st_ttft, 99),
            "decode_steps_total": int(
                sched.metrics.counters["decode_steps"]),
        },
    }, json_path)


def bench_spec_decoding(model, eng, spec, kv_dtype, on_tpu,
                        json_path=None):
    """Speculative (ngram-proposer) vs plain continuous batching on a
    mixed-length REPETITIVE-SUFFIX workload — prompts built by tiling a
    short motif, the regime prompt-lookup exists for (long prompts the
    output echoes; greedy decoding's own repetition loops).  Columns:
    tokens per weight pass (generated tokens over decode+verify passes —
    the quantity speculation raises above 1.0) and draft acceptance
    rate, plus the mean accepted length per verify pass (ISSUE 5
    acceptance: > 1.3 on this workload)."""
    import time as _time
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    n_reqs = int(os.environ.get("SERVE_REQS", 24 if on_tpu else 12))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    max_draft = int(os.environ.get("SERVE_SPEC_K", 8))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    # motif-tiled prompts with a small random head: the suffix n-gram
    # always has an earlier occurrence, mixed lengths keep the batch
    # ragged like the cb bench
    m_lo, m_hi = (4, 9)
    reps_lo, reps_hi = ((8, 24) if on_tpu else (3, 8))
    n_lo, n_hi = ((32, 256) if on_tpu else (12, 48))
    workload = []
    for i in range(n_reqs):
        motif = rng.integers(1, V, (int(rng.integers(m_lo, m_hi)),))
        head = rng.integers(1, V, (int(rng.integers(0, 4)),))
        prompt = np.concatenate(
            [head, np.tile(motif, int(rng.integers(reps_lo, reps_hi)))])
        workload.append((prompt.astype(np.int32),
                         int(rng.integers(n_lo, n_hi))))
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 4
    need = -(-max_len // bs) + 2
    base = dict(block_size=bs, max_num_seqs=max_seqs,
                num_blocks=1 + need * max_seqs,
                max_num_batched_tokens=1 << 30)

    def run(spec_mode):
        cfg = ServingConfig(**base, spec=(
            {"mode": "ngram", "max_draft_tokens": max_draft}
            if spec_mode else {"mode": "off"}))
        sched = ContinuousBatchingScheduler(
            model, eng.params, cfg, kv_cache_dtype=kv_dtype)
        # warm compiles out of the measurement, then measure once (the
        # workload is long enough to swamp dispatch jitter off-TPU too)
        for _ in range(2):
            reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn))
                    for p, nn in workload]
            t0 = _time.time()
            sched.run_until_idle()
            dt = _time.time() - t0
            assert all(len(r.output_ids) == nn
                       for r, (_, nn) in zip(reqs, workload))
        return dt, sched.metrics

    spec_s, spec_m = run(True)
    cb_s, cb_m = run(False)
    c = spec_m.counters
    # weight passes that generated tokens: plain decode scan iterations
    # plus one per spec verify window
    spec_passes = c["decode_steps"] + c["spec_verify_steps"]
    cb_passes = cb_m.counters["decode_steps"]
    h = spec_m.spec_accept_len
    emit({
        "metric": f"{spec}_serve_spec"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": round(useful / spec_s, 1),
        "unit": "tokens_per_sec",
        "detail": {
            "requests": n_reqs, "useful_tokens": useful,
            "max_num_seqs": max_seqs, "max_draft_tokens": max_draft,
            "spec_tok_s": round(useful / spec_s, 1),
            "cb_tok_s": round(useful / cb_s, 1),
            "speedup_vs_cb": round(cb_s / spec_s, 3),
            "spec_tokens_per_weight_pass": round(
                c["generated_tokens"] / max(spec_passes, 1), 3),
            "cb_tokens_per_weight_pass": round(
                cb_m.counters["generated_tokens"] / max(cb_passes, 1), 3),
            "accept_rate": round(
                c["spec_accepted_tokens"] / max(c["spec_drafted_tokens"],
                                                1), 3),
            "mean_accept_len": round(h.sum / max(h.count, 1), 3),
            "drafted": int(c["spec_drafted_tokens"]),
            "accepted": int(c["spec_accepted_tokens"]),
            "rolled_back": int(c["spec_rolled_back_tokens"]),
            "verify_passes": int(c["spec_verify_steps"]),
        },
    }, json_path)


def bench_prefix_cache(model, eng, spec, kv_dtype, on_tpu,
                       json_path=None):
    """Shared-prefix workload (ISSUE 6): N requests drawn over M shared
    system prompts, each with a distinct random tail — the chat-fleet
    regime where most prefill is redundant.  Runs the cb scheduler with
    the prefix cache ON vs OFF (fresh scheduler each, identical
    workload), asserts token-identical outputs, and reports TTFT
    p50/p99, block-granular hit rate, prefill tokens computed (the >=2x
    acceptance column), and serving_goodput."""
    import time as _time
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    n_reqs = int(os.environ.get("SERVE_REQS", 32 if on_tpu else 12))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    n_sys = int(os.environ.get("SERVE_SYS_PROMPTS", 4 if on_tpu else 2))
    sys_len = int(os.environ.get("SERVE_SYS_LEN", 512))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    t_lo, t_hi = ((16, 96) if on_tpu else (4, 16))
    n_lo, n_hi = ((32, 128) if on_tpu else (6, 20))
    systems = [rng.integers(1, V, (sys_len,)).astype(np.int32)
               for _ in range(n_sys)]
    workload = []
    for i in range(n_reqs):
        tail = rng.integers(1, V, (int(rng.integers(t_lo, t_hi)),))
        prompt = np.concatenate([systems[i % n_sys], tail])
        workload.append((prompt.astype(np.int32),
                         int(rng.integers(n_lo, n_hi))))
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 8
    need = -(-max_len // bs) + 1
    # pool sized so the batch fits AND released prefixes can be retained
    # (the steady-state regime the cache serves)
    base = dict(block_size=bs, max_num_seqs=max_seqs,
                num_blocks=1 + need * (max_seqs + n_sys + 1),
                max_num_batched_tokens=1 << 30)

    def run(enabled):
        cfg = ServingConfig(**base,
                            prefix_cache={"enabled": enabled})
        sched = ContinuousBatchingScheduler(
            model, eng.params, cfg, kv_cache_dtype=kv_dtype)
        outs = None
        # warm compiles out of the measurement, then measure (fresh
        # submission wave; the cache persists across waves, as in a
        # long-lived server)
        for _ in range(2):
            reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn))
                    for p, nn in workload]
            t0 = _time.time()
            sched.run_until_idle()
            dt = _time.time() - t0
            assert all(len(r.output_ids) == nn
                       for r, (_, nn) in zip(reqs, workload))
            outs = [list(r.output_ids) for r in reqs]
        ttfts = sorted(r.ttft_s for r in reqs)
        return dt, ttfts, sched.metrics, outs

    on_s, on_ttft, on_m, on_out = run(True)
    off_s, off_ttft, off_m, off_out = run(False)
    assert on_out == off_out, \
        "prefix cache changed greedy output (parity violation)"
    pct = lambda xs, q: round(float(np.percentile(xs, q)) * 1e3, 2)
    c = on_m.counters
    lookups = c["prefix_cache_hit"] + c["prefix_cache_miss"]
    emit({
        "metric": f"{spec}_serve_prefix"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": round(useful / on_s, 1),
        "unit": "tokens_per_sec",
        "detail": {
            "requests": n_reqs, "system_prompts": n_sys,
            "system_len": sys_len, "useful_tokens": useful,
            "max_num_seqs": max_seqs, "block_size": bs,
            "cache_on_tok_s": round(useful / on_s, 1),
            "cache_off_tok_s": round(useful / off_s, 1),
            "speedup_vs_off": round(off_s / on_s, 3),
            "prefill_tokens_on": int(c["prefill_tokens"]),
            "prefill_tokens_off": int(
                off_m.counters["prefill_tokens"]),
            "prefill_reduction": round(
                off_m.counters["prefill_tokens"]
                / max(c["prefill_tokens"], 1), 2),
            "hit_rate": round(c["prefix_cache_hit"] / max(lookups, 1), 3),
            "cow_forks": int(c["prefix_cache_cow_forks"]),
            "evictions": int(c["prefix_cache_evict"]),
            "ttft_on_p50_ms": pct(on_ttft, 50),
            "ttft_on_p99_ms": pct(on_ttft, 99),
            "ttft_off_p50_ms": pct(off_ttft, 50),
            "ttft_off_p99_ms": pct(off_ttft, 99),
            "goodput_on": on_m.gauges.get("goodput"),
            "goodput_off": off_m.gauges.get("goodput"),
        },
    }, json_path)


def bench_kv_tiering(model, eng, spec, kv_dtype, on_tpu,
                     json_path=None):
    """Tiered-KV on/off A/B (ISSUE 16): the shared-prefix workload runs
    twice under a deliberately SMALL hot cache (``max_cached_blocks``
    sized below the working set, so wave-1 prefixes are pushed off the
    LRU before wave 2 re-requests them).  With tiering ON the push is a
    demotion (HBM→host, spilling host→NVMe under ``host_blocks``
    pressure) and wave 2's cold hits pay an async swap-in; with tiering
    OFF the push is an eviction and wave 2 re-prefills.  Token-identical
    greedy outputs are ASSERTED across the two runs; the record carries
    prefill tokens saved, per-tier hit counts, demote/spill/swap-in
    counters, and — when ``DS_NVME_GBPS`` declares a floor — the
    ``swap/achieved_vs_floor`` bandwidth rows (``bench_compare.py``
    gates on the ``*_tok_s`` / ``prefill_*`` keys)."""
    import time as _time
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)
    from deepspeed_tpu.telemetry.iostat import peek_iostat

    n_reqs = int(os.environ.get("SERVE_REQS", 24 if on_tpu else 8))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    n_sys = int(os.environ.get("SERVE_SYS_PROMPTS", 4 if on_tpu else 3))
    sys_len = int(os.environ.get("SERVE_SYS_LEN", 512 if on_tpu else 64))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    t_lo, t_hi = ((16, 96) if on_tpu else (4, 12))
    n_lo, n_hi = ((32, 128) if on_tpu else (4, 10))
    systems = [rng.integers(1, V, (sys_len,)).astype(np.int32)
               for _ in range(n_sys)]
    workload = []
    for i in range(n_reqs):
        tail = rng.integers(1, V, (int(rng.integers(t_lo, t_hi)),))
        prompt = np.concatenate([systems[i % n_sys], tail])
        workload.append((prompt.astype(np.int32),
                         int(rng.integers(n_lo, n_hi))))
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 8
    need = -(-max_len // bs) + 1
    sys_blocks = sys_len // bs
    # hot cache holds ONE system prompt's chain (plus change): the
    # others demote/evict between waves — the spill regime on purpose
    base = dict(block_size=bs, max_num_seqs=max_seqs,
                num_blocks=1 + need * (max_seqs + n_sys + 1),
                max_num_batched_tokens=1 << 30)

    def run(enabled):
        cfg = ServingConfig(
            **base,
            prefix_cache={"enabled": True,
                          "max_cached_blocks": sys_blocks + 1},
            kv_tiering={"enabled": enabled,
                        # host holds one more system's worth; the rest
                        # spills onward to NVMe
                        "host_blocks": sys_blocks,
                        "nvme_blocks": 0})
        sched = ContinuousBatchingScheduler(
            model, eng.params, cfg, kv_cache_dtype=kv_dtype)
        outs = None
        for _ in range(2):
            reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn))
                    for p, nn in workload]
            t0 = _time.time()
            sched.run_until_idle()
            dt = _time.time() - t0
            assert all(len(r.output_ids) == nn
                       for r, (_, nn) in zip(reqs, workload))
            outs = [list(r.output_ids) for r in reqs]
        return dt, sched.metrics, outs

    on_s, on_m, on_out = run(True)
    off_s, off_m, off_out = run(False)
    assert on_out == off_out, \
        "tiered KV changed greedy output (parity violation)"
    c = on_m.counters
    swapped = int(c["kv_swap_in_blocks"])
    io = peek_iostat()
    io_rows = io.summary() if io is not None else {}
    emit({
        "metric": f"{spec}_serve_tier"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": round(useful / on_s, 1),
        "unit": "tokens_per_sec",
        "detail": {
            "requests": n_reqs, "system_prompts": n_sys,
            "system_len": sys_len, "useful_tokens": useful,
            "max_num_seqs": max_seqs, "block_size": bs,
            "hot_cache_blocks": sys_blocks + 1,
            "host_tier_blocks": sys_blocks,
            "tier_on_tok_s": round(useful / on_s, 1),
            "tier_off_tok_s": round(useful / off_s, 1),
            "prefill_tokens_on": int(c["prefill_tokens"]),
            "prefill_tokens_off": int(
                off_m.counters["prefill_tokens"]),
            "prefill_tokens_saved": int(
                off_m.counters["prefill_tokens"]
                - c["prefill_tokens"]),
            "swap_in_blocks": swapped,
            "swap_in_tokens": swapped * bs,
            "tier_hits_host": int(c["kv_tier_hit_host"]),
            "tier_hits_nvme": int(c["kv_tier_hit_nvme"]),
            "demotions": int(c["kv_demotions"]),
            "spills": int(c["kv_spills"]),
            "swap_failures": int(c["kv_swap_failures"]),
            "tier_hit_rate": on_m.gauges.get("kv_tier_hit_rate"),
            "evictions_off": int(
                off_m.counters["prefix_cache_evict"]),
            "swap_io": io_rows,
            "swap_read_vs_floor": (io_rows.get("ops", {})
                                   .get("read", {}).get("vs_floor")),
            "swap_write_vs_floor": (io_rows.get("ops", {})
                                    .get("write", {}).get("vs_floor")),
        },
    }, json_path)


def bench_lora_multitenant(model, eng, spec, kv_dtype, on_tpu,
                           json_path=None):
    """Multi-tenant LoRA A/B (ISSUE 20): N tenants' adapters serve from
    the paged AdapterStore with FEWER HBM slots than tenants, so the
    round-robin workload keeps adapters paging between HBM and the host
    tier (mixed hot/cold on purpose).  The paged run batches every
    tenant — plus adapter-less base rows — into ONE unified window via
    batched gather-LoRA; the A/B alternative is the dedicated-weights
    deployment it replaces: one ``merge_lora`` scheduler per tenant,
    serialized (no cross-tenant batching — that is the point).
    Token-identical greedy outputs are ASSERTED between the two.  The
    record carries both throughputs, the store's swap-in / demotion /
    spill / slot-wait counters, the fraction of swap-in-pending steps
    that still produced decode tokens (swap-in hidden behind running
    decode), and per-tenant mean TTFT."""
    import time as _time
    import jax as _jax
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.runtime.lora import init_lora_params, merge_lora
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    n_tenants = int(os.environ.get("SERVE_TENANTS", 6 if on_tpu else 4))
    hbm_slots = int(os.environ.get(
        "SERVE_HBM_ADAPTERS", max(2, n_tenants // 2) if on_tpu else 2))
    n_reqs = int(os.environ.get("SERVE_REQS", 24 if on_tpu else 12))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    p_lo, p_hi = ((32, 128) if on_tpu else (4, 12))
    n_lo, n_hi = ((32, 96) if on_tpu else (4, 10))

    def mk_lora(seed):
        # init_lora_params zeros B (merged == base) — randomize it so
        # every tenant is distinguishable from the base model
        lora = init_lora_params(eng.params, rank=4,
                                rng=_jax.random.PRNGKey(seed))
        r2 = np.random.default_rng(seed)
        return {p: {"a": np.asarray(ab["a"]),
                    "b": r2.normal(0, 0.05, ab["b"].shape).astype(
                        np.float32)}
                for p, ab in lora.items()}

    tenants = [f"t{i}" for i in range(n_tenants)]
    loras = {t: mk_lora(100 + i) for i, t in enumerate(tenants)}
    # round-robin over base + every tenant: adapter-less rows ride the
    # same unified window and must skip the gather-LoRA pass exactly
    ids = [None] + tenants
    workload = []
    for i in range(n_reqs):
        prompt = rng.integers(
            1, V, (int(rng.integers(p_lo, p_hi)),)).astype(np.int32)
        workload.append((ids[i % len(ids)], prompt,
                         int(rng.integers(n_lo, n_hi))))
    useful = sum(nn for _, _, nn in workload)

    bs = 16 if on_tpu else 8
    max_len = max(p.size + nn for _, p, nn in workload)
    need = -(-max_len // bs) + 1
    base = dict(block_size=bs, max_num_seqs=max_seqs,
                num_blocks=1 + need * (max_seqs + 1),
                max_num_batched_tokens=1 << 30)

    # paged run: adapters register COLD (host tier); fewer HBM slots
    # than tenants keeps the store paging under the round-robin
    cfg = ServingConfig(**base, adapters={"enabled": True,
                                          "max_hbm_adapters": hbm_slots})
    sched = ContinuousBatchingScheduler(model, eng.params, cfg,
                                        kv_cache_dtype=kv_dtype)
    for t in tenants:
        sched.register_adapter(t, lora_tree=loras[t])
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn),
                         adapter_id=t)
            for t, p, nn in workload]
    pending_steps = overlap_steps = 0
    decoded_prev = 0
    t0 = _time.time()
    while sched.has_work():
        waiting = bool(sched._adapter_pending)
        sched.step()
        decoded = sum(len(r.output_ids) for r in reqs)
        if waiting:
            pending_steps += 1
            if decoded > decoded_prev:
                overlap_steps += 1   # swap-in hid behind running decode
        decoded_prev = decoded
    paged_s = _time.time() - t0
    paged_out = [list(r.output_ids) for r in reqs]
    assert all(len(o) == nn
               for o, (_, _, nn) in zip(paged_out, workload))

    ttft = {}
    for (t, _, _), r in zip(workload, reqs):
        ttft.setdefault(t or "base", []).append(r.ttft_s * 1e3)
    ttft_ms = {k: round(float(np.mean(v)), 3)
               for k, v in sorted(ttft.items())}

    # merged A/B: the dedicated-weights alternative — one offline
    # merge_lora scheduler per tenant, serialized; the parity oracle
    merged_out = [None] * len(workload)
    t0 = _time.time()
    for t in ids:
        mp = (merge_lora(eng.params, loras[t], 1.0, freeze_base=False)
              if t else eng.params)
        s2 = ContinuousBatchingScheduler(model, mp, ServingConfig(**base),
                                         kv_cache_dtype=kv_dtype)
        mine = [(j, p, nn) for j, (tt, p, nn) in enumerate(workload)
                if tt == t]
        rs = [s2.submit(p, SamplingParams(max_new_tokens=nn))
              for _, p, nn in mine]
        s2.run_until_idle()
        for (j, _, _), r in zip(mine, rs):
            merged_out[j] = list(r.output_ids)
    merged_s = _time.time() - t0
    assert paged_out == merged_out, \
        "paged gather-LoRA drifted from the offline-merged oracle"

    st = sched.adapter_store.summary()
    emit({
        "metric": f"{spec}_serve_lora"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": round(useful / paged_s, 1),
        "unit": "tokens_per_sec",
        "detail": {
            "tenants": n_tenants, "hbm_adapter_slots": hbm_slots,
            "requests": n_reqs, "useful_tokens": useful,
            "max_num_seqs": max_seqs, "block_size": bs,
            "paged_tok_s": round(useful / paged_s, 1),
            "merged_tok_s": round(useful / merged_s, 1),
            "token_identical": True,
            "swap_ins": int(st["swap_ins"]),
            "demotions": int(st["demotions"]),
            "spills": int(st["spills"]),
            "slot_waits": int(st["slot_waits"]),
            "swapin_pending_steps": pending_steps,
            "swapin_overlap_steps": overlap_steps,
            "swapin_overlap_fraction": (
                round(overlap_steps / pending_steps, 3)
                if pending_steps else None),
            "ttft_ms_by_tenant": ttft_ms,
        },
    }, json_path)


def bench_slo_chunked(model, eng, spec, kv_dtype, on_tpu,
                      json_path=None):
    """Adversarial heavy-prefill overload (ISSUE 9): a steady pool of
    short ``chat``-class streams decodes while a few long ``batch``-class
    prompts arrive mid-flight (at fixed scheduler step counts, identical
    in both runs).  A/B: chunked prefill ON vs OFF, token-identical
    greedy outputs asserted.  The record carries p50/p99 TPOT + TTFT per
    class for both runs — ``bench_compare.py`` gates regressions on the
    ``*_ms`` keys (lower-better inferred).  The acceptance column is
    ``chat_tpot_p99_ms``: bounded with chunking on, spiking with it off
    (each spike = one long prompt's whole prefill inside one scheduler
    iteration, stalling every chat stream)."""
    import time as _time
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    n_chat = int(os.environ.get("SERVE_REQS", 16 if on_tpu else 6))
    n_long = int(os.environ.get("SERVE_LONG", 2))
    # off-TPU the long prompts must be long enough that the one-shot
    # prefill's quadratic attention dwarfs a chunk window's cost — the
    # verify-window programs are per-position compute off-chip (the PR 6
    # CPU-crossover caveat); on TPU the regime is the real one
    long_len = int(os.environ.get("SERVE_LONG_LEN",
                                  8192 if on_tpu else 640))
    chunk_tokens = int(os.environ.get("SERVE_CHUNK",
                                      512 if on_tpu else 64))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    arrival_step = int(os.environ.get("SERVE_ARRIVAL_STEP", 8))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    p_lo, p_hi = ((32, 128) if on_tpu else (6, 24))
    chat_new = int(os.environ.get("SERVE_TOKENS", 128 if on_tpu else 48))
    chat = [(rng.integers(1, V, (int(pl),)).astype(np.int32), chat_new)
            for pl in rng.integers(p_lo, p_hi, n_chat)]
    longs = [(rng.integers(1, V, (long_len,)).astype(np.int32),
              8 if on_tpu else 4) for _ in range(n_long)]
    bs = 16 if on_tpu else 8
    max_len = max(p.size + nn for p, nn in chat + longs)
    need = -(-max_len // bs) + 1
    base = dict(
        block_size=bs, max_num_seqs=max_seqs,
        num_blocks=1 + need * (max_seqs + n_long),
        # a realistic per-iteration budget (not the other modes' 1<<30):
        # the whole point is that chunking turns it into a REAL cap
        max_num_batched_tokens=max(2048, chunk_tokens * 2),
        # unfused decode: every chat token's timestamp is one scheduler
        # iteration, so the inter-token gap IS the interference signal
        # (a fused window emits k tokens with one timestamp and buries
        # the spike in zero-width gaps)
        max_fused_steps=1,
        slo={"enabled": True,
             "classes": {"chat": {"tpot_ms": 200.0, "priority": 1},
                         "batch": {"priority": 0}}})

    def run(chunked):
        cfg = ServingConfig(**base, chunked_prefill={
            "enabled": chunked, "chunk_tokens": chunk_tokens})
        sched = ContinuousBatchingScheduler(
            model, eng.params, cfg, kv_cache_dtype=kv_dtype)
        outs = None
        max_step_prefill = 0
        for _ in range(2):          # warm compiles, then measure
            creqs = [sched.submit(p, SamplingParams(max_new_tokens=nn),
                                  slo_class="chat") for p, nn in chat]
            lreqs = []
            t0 = _time.time()
            steps = 0
            max_step_prefill = 0
            while sched.has_work() or len(lreqs) < n_long:
                sched.step()
                steps += 1
                # the boundedness witness: the largest prefill spend any
                # single iteration saw — chunked it stays ~chunk_tokens,
                # unchunked it is the whole long prompt in one iteration
                max_step_prefill = max(
                    max_step_prefill,
                    int(sched.metrics.gauges.get("step_prefill_tokens",
                                                 0)))
                # long prompts arrive mid-flight, one per arrival
                # window, while the chat pool is mid-decode — the
                # step-keyed schedule is identical across the A/B
                if steps % arrival_step == 0 and len(lreqs) < n_long:
                    p, nn = longs[len(lreqs)]
                    lreqs.append(sched.submit(
                        p, SamplingParams(max_new_tokens=nn),
                        slo_class="batch"))
            dt = _time.time() - t0
            reqs = creqs + lreqs
            assert all(len(r.output_ids) == nn for r, (_, nn) in
                       zip(reqs, chat + longs))
            outs = [list(r.output_ids) for r in reqs]
        # per-class latency shape: TPOT = every inter-token gap (the
        # spike detector — a one-iteration 32k prefill shows up as one
        # huge gap in EVERY concurrent chat stream), TTFT per request
        gaps = {"chat": [], "batch": []}
        ttfts = {"chat": [], "batch": []}
        for cls, rs in (("chat", creqs), ("batch", lreqs)):
            for r in rs:
                ttfts[cls].append(r.ttft_s)
                ts = r.token_times
                gaps[cls].extend(b - a for a, b in zip(ts, ts[1:]))
        return dt, gaps, ttfts, outs, sched.metrics, max_step_prefill

    on_s, on_gaps, on_ttft, on_out, on_m, on_maxpf = run(True)
    off_s, off_gaps, off_ttft, off_out, off_m, off_maxpf = run(False)
    assert on_out == off_out, \
        "chunked prefill changed greedy output (parity violation)"
    pct = lambda xs, q: (round(float(np.percentile(xs, q)) * 1e3, 2)
                         if xs else None)
    useful = sum(nn for _, nn in chat + longs)
    # the backend-independent boundedness witness: with chunking on, no
    # single iteration may execute (much) more prefill than the chunk
    # allowance (window bucket rounding allows a few tokens of slack);
    # with it off, the long prompt's whole prefill lands in ONE iteration
    assert on_maxpf <= chunk_tokens + 64, \
        (f"chunked max per-iteration prefill {on_maxpf} blew the "
         f"chunk_tokens={chunk_tokens} allowance")
    assert off_maxpf >= long_len, \
        "unchunked run never monopolized an iteration — workload too small"
    detail = {
        "chat_requests": n_chat, "long_requests": n_long,
        "long_len": long_len, "chunk_tokens": chunk_tokens,
        "max_num_seqs": max_seqs, "block_size": bs,
        "chunked_tok_s": round(useful / on_s, 1),
        "unchunked_tok_s": round(useful / off_s, 1),
        "max_step_prefill_tokens_on": on_maxpf,
        "max_step_prefill_tokens_off": off_maxpf,
        "chunks_deferred": int(on_m.counters["chunks_deferred"]),
        "slo_violations_on": int(on_m.counters["slo_violations"]),
        "slo_violations_off": int(off_m.counters["slo_violations"]),
    }
    for cls in ("chat", "batch"):
        detail.update({
            f"{cls}_tpot_p50_ms": pct(on_gaps[cls], 50),
            f"{cls}_tpot_p99_ms": pct(on_gaps[cls], 99),
            f"{cls}_tpot_max_ms": pct(on_gaps[cls], 100),
            f"{cls}_ttft_p50_ms": pct(on_ttft[cls], 50),
            f"{cls}_ttft_p99_ms": pct(on_ttft[cls], 99),
            f"{cls}_tpot_p50_off_ms": pct(off_gaps[cls], 50),
            f"{cls}_tpot_p99_off_ms": pct(off_gaps[cls], 99),
            f"{cls}_tpot_max_off_ms": pct(off_gaps[cls], 100),
            f"{cls}_ttft_p50_off_ms": pct(off_ttft[cls], 50),
            f"{cls}_ttft_p99_off_ms": pct(off_ttft[cls], 99),
        })
    emit({
        "metric": f"{spec}_serve_slo"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": detail["chat_tpot_p99_ms"],
        "unit": "chat_p99_tpot_ms",
        "detail": detail,
    }, json_path)


def bench_fleet_routing(model, eng, spec, kv_dtype, on_tpu,
                        json_path=None):
    """Shared-prefix workload through the fleet Router (ISSUE 11):
    N requests over M shared system prompts dispatched across
    ``SERVE_REPLICAS`` replica schedulers, submitted in waves (the
    steady-traffic regime — routing decisions see the caches earlier
    waves populated).  A/B: the prefix-aware scored policy vs
    round-robin, token-identical greedy outputs asserted; the record
    carries the aggregate prefix-cache hit rate per policy (the
    acceptance column: scored > round_robin) plus per-replica dispatch
    counts and resubmit/misroute counters."""
    import time as _time
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import SamplingParams
    from deepspeed_tpu.serving.fleet import Replica, Router

    n_replicas = int(os.environ.get("SERVE_REPLICAS", 2))
    n_reqs = int(os.environ.get("SERVE_REQS", 32 if on_tpu else 12))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    n_sys = int(os.environ.get("SERVE_SYS_PROMPTS", 4 if on_tpu else 3))
    sys_len = int(os.environ.get("SERVE_SYS_LEN", 512))
    wave = int(os.environ.get("SERVE_WAVE", max(n_replicas * 2, 4)))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    t_lo, t_hi = ((16, 96) if on_tpu else (4, 16))
    n_lo, n_hi = ((32, 128) if on_tpu else (6, 20))
    systems = [rng.integers(1, V, (sys_len,)).astype(np.int32)
               for _ in range(n_sys)]
    workload = []
    for i in range(n_reqs):
        tail = rng.integers(1, V, (int(rng.integers(t_lo, t_hi)),))
        prompt = np.concatenate([systems[int(rng.integers(n_sys))], tail])
        workload.append((prompt.astype(np.int32),
                         int(rng.integers(n_lo, n_hi))))
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 8
    need = -(-max_len // bs) + 1
    base = dict(block_size=bs, max_num_seqs=max_seqs,
                num_blocks=1 + need * (max_seqs + n_sys + 1),
                max_num_batched_tokens=1 << 30,
                prefix_cache={"enabled": True})

    def run(policy):
        cfg = ServingConfig(**base, fleet={
            "num_replicas": n_replicas, "policy": policy,
            # always-fresh digests: the A/B measures the POLICY, not
            # digest staleness
            "digest_refresh_s": 0})
        replicas = [Replica(i, model, eng.params, cfg,
                            kv_cache_dtype=kv_dtype)
                    for i in range(n_replicas)]
        router = Router(replicas, cfg.fleet)

        def dispatch_counts():
            return {str(r.replica_id): int(router.registry.get_counter(
                "fleet/dispatches", replica=str(r.replica_id)))
                for r in replicas}

        outs, warm = None, {}
        for it in range(2):         # warm compiles, then measure
            handles = []
            t0 = _time.time()
            for i in range(0, n_reqs, wave):
                handles.extend(
                    router.submit(p, SamplingParams(max_new_tokens=nn))
                    for p, nn in workload[i:i + wave])
                router.run_until_idle()
            dt = _time.time() - t0
            assert all(len(h.output_ids) == nn
                       for h, (_, nn) in zip(handles, workload))
            outs = [list(h.output_ids) for h in handles]
            if it == 0:
                warm = dispatch_counts()   # the record reports only the
        counts = {rid: n - warm.get(rid, 0)  # measured pass's spread
                  for rid, n in dispatch_counts().items()}
        return dt, outs, router.aggregate_prefix_hit_rate(), counts

    sc_s, sc_out, sc_hit, sc_counts = run("scored")
    rr_s, rr_out, rr_hit, rr_counts = run("round_robin")
    assert sc_out == rr_out, \
        "routing policy changed greedy output (parity violation)"
    if n_replicas > 1 and n_sys > 1:
        # the acceptance column: concentrating same-prefix traffic can
        # never LOSE to scattering it (strictly above on the default
        # smoke: 0.873 vs 0.831 — see PERF.md PR 11)
        assert sc_hit >= rr_hit, \
            (f"prefix-aware routing hit rate {sc_hit} fell below "
             f"round-robin {rr_hit}")
    emit({
        "metric": f"{spec}_serve_fleet"
                  + ("_int8kv" if kv_dtype == "int8" else ""),
        "value": round(useful / sc_s, 1),
        "unit": "tokens_per_sec",
        "detail": {
            "replicas": n_replicas, "requests": n_reqs,
            "system_prompts": n_sys, "system_len": sys_len,
            "wave": wave, "useful_tokens": useful,
            "max_num_seqs": max_seqs, "block_size": bs,
            "scored_tok_s": round(useful / sc_s, 1),
            "round_robin_tok_s": round(useful / rr_s, 1),
            "prefix_hit_rate_scored": (round(sc_hit, 4)
                                       if sc_hit is not None else None),
            "prefix_hit_rate_round_robin": (
                round(rr_hit, 4) if rr_hit is not None else None),
            "dispatches_scored": sc_counts,
            "dispatches_round_robin": rr_counts,
        },
    }, json_path)


def bench_moe_dispatch(model, eng, spec, kv_dtype, quant, on_tpu,
                       json_path=None):
    """Mixtral expert-dispatch A/B (ISSUE 8): the same mixed-length cb
    workload through the scheduler with grouped (megablocks-style ragged
    grouped GEMM, ops/pallas/grouped_gemm.py) vs einsum (GShard [T,E,C]
    capacity tensors) dispatch — greedy outputs asserted token-identical
    (eval einsum capacity is drop-free by MixtralConfig default, so the
    two formulations compute the same math).  With SERVE_INT8_WEIGHTS=1
    the grouped path consumes the int8 expert stacks in place through
    the fused-dequant grouped kernel and the record carries the
    ``weights_floor_moe`` accounting: dense int8 bytes + top-k-DISTINCT-
    expert bytes per decode step — the floor the grouped path streams
    at, vs all-E-experts for einsum's dense dispatch."""
    import time as _time
    from deepspeed_tpu.moe.layer import dispatch_scope, gg_kernel_real
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)

    moe_cfg = getattr(model.config, "moe", None)
    if moe_cfg is None:
        raise SystemExit(f"SERVE_MODE=moe needs a routed-expert model "
                         f"(got {spec}) — e.g. SERVE_MODEL=mixtral:1b-moe")

    n_reqs = int(os.environ.get("SERVE_REQS", 24 if on_tpu else 8))
    max_seqs = int(os.environ.get("SERVE_B", 8 if on_tpu else 4))
    p_lo, p_hi = ((32, 768) if on_tpu else (4, 24))
    n_lo, n_hi = ((8, 384) if on_tpu else (4, 16))
    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    workload = [
        (rng.integers(1, V, (int(pl),)).astype(np.int32), int(nn))
        for pl, nn in zip(rng.integers(p_lo, p_hi, n_reqs),
                          rng.integers(n_lo, n_hi, n_reqs))]
    useful = sum(nn for _, nn in workload)
    max_len = max(p.size + nn for p, nn in workload)
    bs = 16 if on_tpu else 4
    need = -(-max_len // bs) + 1
    base = dict(block_size=bs, max_num_seqs=max_seqs,
                num_blocks=1 + need * max_seqs,
                max_num_batched_tokens=1 << 30)

    def run(mode):
        # fresh scheduler per mode: per-instance jit caches, and the
        # dispatch choice is resolved at trace time inside the scope
        with dispatch_scope(mode):
            cfg = ServingConfig(**base)
            sched = ContinuousBatchingScheduler(
                model, eng.params, cfg, kv_cache_dtype=kv_dtype)
            outs = None
            for _ in range(2):      # warm compiles, then measure
                reqs = [sched.submit(p, SamplingParams(max_new_tokens=nn))
                        for p, nn in workload]
                t0 = _time.time()
                sched.run_until_idle()
                dt = _time.time() - t0
                assert all(len(r.output_ids) == nn
                           for r, (_, nn) in zip(reqs, workload))
                outs = [list(r.output_ids) for r in reqs]
        return dt, outs

    g_s, g_out = run("grouped")
    e_s, e_out = run("einsum")
    assert g_out == e_out, \
        "grouped dispatch changed greedy output (parity violation)"

    detail = {
        "requests": n_reqs, "useful_tokens": useful,
        "max_num_seqs": max_seqs, "block_size": bs,
        "num_experts": moe_cfg.num_experts, "top_k": moe_cfg.top_k,
        "grouped_tok_s": round(useful / g_s, 1),
        "einsum_tok_s": round(useful / e_s, 1),
        "speedup_vs_einsum": round(e_s / g_s, 3),
        "grouped_kernel_real": gg_kernel_real(),
        "int8_weights": bool(quant),
    }
    if quant:
        # weights_floor_moe: per decode step the grouped int8 path
        # streams every DENSE int8 byte once plus, per layer, only the
        # distinct routed experts' bytes (<= min(active_rows * top_k, E)
        # — the slot plan fetches each distinct expert's weight block
        # exactly once); einsum dispatch streams all E experts' bytes
        from deepspeed_tpu.models.serving import split_quantized_bytes
        dense_b, expert_b = split_quantized_bytes(eng.params["blocks"])
        E, k = moe_cfg.num_experts, moe_cfg.top_k
        per_expert = expert_b // max(E, 1)      # all layers, one expert
        distinct = min(max_seqs * k, E)
        detail.update({
            "dense_int8_bytes": dense_b,
            "expert_int8_bytes_total": expert_b,
            "weights_floor_moe_bytes": dense_b + distinct * per_expert,
            "einsum_stream_bytes": dense_b + expert_b,
            "distinct_experts_bound": distinct,
        })
    emit({
        "metric": f"{spec}_serve_moe"
                  + ("_int8kv" if kv_dtype == "int8" else "")
                  + ("_int8w" if quant else ""),
        "value": round(useful / g_s, 1),
        "unit": "tokens_per_sec",
        "detail": detail,
    }, json_path)


if __name__ == "__main__":
    main()
