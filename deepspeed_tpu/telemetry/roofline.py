"""Roofline attribution over the cost model (ISSUE 13 tentpole).

``mfu.py`` answers "what fraction of peak FLOPs did we achieve";
this module answers the decode-regime question PERF.md has been
answering by hand: **what is the hardware floor for this program, and
how far above it are we running**.  A per-device HBM-bandwidth table
(same shape as ``PEAK_FLOPS_BY_KIND``) prices a program's
:class:`~deepspeed_tpu.telemetry.costmodel.CostReport` into

- ``floor_ms`` — ``max(flops/peak, hbm_bytes/bandwidth)`` per
  execution, the roofline lower bound;
- a compute-bound vs bandwidth-bound classification (which term won);
- ``achieved_vs_floor`` — measured wall clock over the floor, the
  "4-5x-over-floor" gap as a live gauge instead of a PERF.md table.

On CPU neither table resolves and every floor-dependent output is None
— **no fictitious floors**.  ``DS_HBM_GBPS`` overrides per device
(it is also how CPU tier-1 tests exercise the floor math).  Gauges
land in the shared metrics registry under ``perf/*`` labeled by
program, on both /metrics surfaces.
"""
import os
from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry import costmodel as _cm
from deepspeed_tpu.telemetry.mfu import peak_flops_per_device

HBM_GBPS_ENV = "DS_HBM_GBPS"

#: HBM bandwidth per chip (GB/s) by device-kind substring (lowercase).
#: Sources: published TPU system specs (per-chip).
HBM_GBPS_BY_KIND = {
    "v5p": 2765.0,
    "v5e": 819.0,
    "v5litepod": 819.0,
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}


def hbm_bytes_per_s(device=None, env: Optional[dict] = None
                    ) -> Optional[float]:
    """HBM bandwidth for one device in bytes/s: ``DS_HBM_GBPS`` env
    wins, then the device-kind table; None when unknown (CPU, exotic
    parts) — callers must skip floor math rather than report against a
    made-up bandwidth."""
    env = os.environ if env is None else env
    override = env.get(HBM_GBPS_ENV, "").strip()
    if override:
        return float(override) * 1e9
    if device is None:
        import jax
        device = jax.local_devices()[0]
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, gbps in HBM_GBPS_BY_KIND.items():
        if sub in kind:
            return gbps * 1e9
    return None


def floor_seconds(report, peak_flops: Optional[float] = None,
                  hbm_bps: Optional[float] = None) -> Optional[float]:
    """Roofline lower bound for one execution: the slower of the
    compute term and the bandwidth term, over the terms whose hardware
    rate is known.  None when neither rate resolves."""
    terms = []
    if peak_flops and peak_flops > 0 and report.flops > 0:
        terms.append(report.flops / peak_flops)
    if hbm_bps and hbm_bps > 0 and report.hbm_bytes > 0:
        terms.append(report.hbm_bytes / hbm_bps)
    if not terms:
        return None
    return max(terms)


def classify(report, peak_flops: Optional[float] = None,
             hbm_bps: Optional[float] = None) -> Optional[str]:
    """"compute_bound" / "bandwidth_bound" by which roofline term
    dominates; None when the comparison needs a rate we don't have."""
    if not (peak_flops and hbm_bps and report.flops > 0
            and report.hbm_bytes > 0):
        return None
    compute_s = report.flops / peak_flops
    memory_s = report.hbm_bytes / hbm_bps
    return "compute_bound" if compute_s >= memory_s else "bandwidth_bound"


#: (DS_HBM_GBPS, DS_PEAK_FLOPS) env values -> resolved rates; the
#: device kind is constant per process, so rates only change when the
#: env overrides do — observe_achieved runs per decode step and must
#: not pay jax.local_devices + table walks every time
_RATES_CACHE: Dict[tuple, Dict[str, Optional[float]]] = {}


def device_rates(env: Optional[dict] = None) -> Dict[str, Optional[float]]:
    """(peak_flops, hbm_bps) for the first local device, None-safe on
    any backend (one place resolves both tables + envs).  Cached per
    (env-override) pair; pass an explicit ``env`` dict to bypass the
    cache (tests)."""
    from deepspeed_tpu.telemetry.mfu import PEAK_FLOPS_ENV
    cache_key = None
    if env is None:
        cache_key = (os.environ.get(HBM_GBPS_ENV, ""),
                     os.environ.get(PEAK_FLOPS_ENV, ""))
        hit = _RATES_CACHE.get(cache_key)
        if hit is not None:
            return hit
    try:
        import jax
        dev = jax.local_devices()[0]
    except Exception:
        dev = None
    try:
        peak = peak_flops_per_device(dev, env=env) if dev is not None \
            else None
    except Exception:
        peak = None
    try:
        bw = hbm_bytes_per_s(dev, env=env) if dev is not None else None
    except Exception:
        bw = None
    rates = {"peak_flops": peak, "hbm_bytes_per_s": bw,
             "device_kind": str(getattr(dev, "device_kind", "unknown"))}
    if cache_key is not None:
        _RATES_CACHE[cache_key] = rates
    return rates


def publish_report(registry, report):
    """Static cost gauges for one program family, labeled by program —
    rendered identically by ds_serve /metrics and the training
    endpoint.  Floor gauges appear only when a hardware rate resolves
    (no fictitious floors on CPU)."""
    _cm.register_report(report)
    name = report.name
    registry.set_gauge("perf/flops", float(report.flops), program=name)
    registry.set_gauge("perf/hbm_bytes", float(report.hbm_bytes),
                       program=name)
    registry.set_gauge("perf/pallas_launches",
                       float(report.pallas_launches), program=name)
    registry.set_gauge("perf/collective_bytes",
                       float(report.collective_bytes), program=name)
    rates = device_rates()
    floor = floor_seconds(report, rates["peak_flops"],
                          rates["hbm_bytes_per_s"])
    if floor is not None:
        registry.set_gauge("perf/floor_ms", floor * 1e3, program=name)


def observe_achieved(registry, name: str, duration_s: float):
    """One measured execution of a registered program: updates the
    lock-free achieved table and the ``perf/achieved_ms`` gauge, and —
    when the program's floor resolves — the ``perf/achieved_vs_floor``
    ratio (the live "N-x-over-floor" gap)."""
    _cm.record_achieved(name, duration_s)
    registry.set_gauge("perf/achieved_ms", duration_s * 1e3, program=name)
    report = _cm.get_report(name)
    if report is None:
        return
    rates = device_rates()
    floor = floor_seconds(report, rates["peak_flops"],
                          rates["hbm_bytes_per_s"])
    if floor and floor > 0:
        registry.set_gauge("perf/achieved_vs_floor",
                           duration_s / floor, program=name)


def perf_table(env: Optional[dict] = None) -> Dict[str, Any]:
    """The ``/debug/perf`` body and the post-mortem ``perf.json``
    payload: device rates + one row per registered program (static
    cost, floor, classification, live achieved stats).  Lock-free with
    respect to every subsystem it reports on — safe to hit while a
    step is wedged."""
    rates = device_rates(env=env)
    peak, bw = rates["peak_flops"], rates["hbm_bytes_per_s"]
    achieved = _cm.get_achieved()
    programs = {}
    for name, report in sorted(_cm.get_reports().items()):
        row = report.to_dict()
        floor = floor_seconds(report, peak, bw)
        row["floor_ms"] = None if floor is None else round(floor * 1e3, 6)
        row["bound"] = classify(report, peak, bw)
        a = achieved.get(name)
        if a is not None:
            last_ms, count, total_ms = a
            row["achieved_ms"] = round(last_ms, 6)
            row["achieved_count"] = count
            # the first sample (compile + analysis trace) is excluded
            # from the total — the mean is over warm executions
            row["achieved_mean_ms"] = round(
                total_ms / (count - 1) if count > 1 else last_ms, 6)
            if floor and floor > 0:
                row["achieved_vs_floor"] = round(
                    (last_ms / 1e3) / floor, 4)
        programs[name] = row
    return {
        "device_kind": rates["device_kind"],
        "peak_flops": peak,
        "hbm_gbps": None if bw is None else bw / 1e9,
        "programs": programs,
    }
