"""1-bit optimizer tests (reference: tests/unit/runtime/half_precision/onebit/
test_onebit.py + tests/onebit/ comm micro-tests)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

import deepspeed_tpu
from deepspeed_tpu.runtime.comm.compressed import (compress,
                                                   compressed_allreduce)
from deepspeed_tpu.runtime.fp16.onebit.adam import onebit_adam
from tests.util import tiny_gpt2, base_config, random_batches


def test_compress_sign_and_scale():
    v = jnp.asarray([1.0, -2.0, 3.0, -4.0])
    sign, scale = compress(v)
    assert sign.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(sign), [1, -1, 1, -1])
    assert float(scale) == 2.5                      # mean |v|


def test_compressed_allreduce_error_feedback(devices8):
    """The compressed mean approximates the exact mean, and the residual is
    exactly what compression dropped (error feedback invariant)."""
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rng = np.random.default_rng(0)
    local = rng.normal(size=(8, 128)).astype(np.float32)
    x = jax.device_put(jnp.asarray(local), NamedSharding(mesh, P("dp", None)))

    def body(v):
        red, err = compressed_allreduce(v[0], jnp.zeros_like(v[0]), "dp")
        return red[None], err[None]

    red, err = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                         out_specs=(P(None, None), P("dp", None)),
                         check_vma=False)(x)
    exact = local.mean(axis=0)
    got = np.asarray(red)[0]
    # sign*mean-magnitude keeps the direction: correlation must be high
    corr = np.corrcoef(got, exact)[0, 1]
    assert corr > 0.5, corr
    # per-device residual == corrected - scale*sign
    e0 = np.asarray(err)[0]
    scale0 = np.abs(local[0]).mean()
    expect0 = local[0] - scale0 * np.sign(local[0])
    np.testing.assert_allclose(e0, expect0, rtol=1e-5, atol=1e-5)


def test_compressed_allreduce_error_feedback_unbiases(devices8):
    """Repeatedly reducing the SAME gradient with error feedback converges
    to the exact mean (the 1-bit Adam correctness argument)."""
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rng = np.random.default_rng(1)
    local = rng.normal(size=(8, 64)).astype(np.float32)
    x = jax.device_put(jnp.asarray(local), NamedSharding(mesh, P("dp", None)))
    exact = local.mean(axis=0)

    def body(v):
        err = jnp.zeros_like(v[0])
        srv = jnp.zeros((v[0].size // 8,), jnp.float32)
        acc = jnp.zeros_like(v[0])

        def step(carry, _):
            err, srv, acc = carry
            red, err, srv = compressed_allreduce(v[0], err, "dp",
                                                 server_error=srv)
            return (err, srv, acc + red), None

        (err, srv, acc), _ = jax.lax.scan(step, (err, srv, acc), None,
                                          length=20)
        return (acc / 20)[None]

    avg = np.asarray(shard_map(body, mesh=mesh, in_specs=P("dp", None),
                               out_specs=P(None, None),
                               check_vma=False)(x))[0]
    # with both worker and server error feedback, the time-averaged
    # compressed reduction converges to the exact mean
    np.testing.assert_allclose(avg, exact, atol=0.25)
    assert np.abs(avg - exact).mean() < np.abs(exact).mean()


def test_onebit_adam_matches_adam_during_warmup():
    import optax
    rng = np.random.default_rng(2)
    params = {"w": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    g = {"w": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    ob = onebit_adam(learning_rate=0.1, freeze_step=100)
    ad = optax.adam(0.1)
    s1, s2 = ob.init(params), ad.init(params)
    p1, p2 = params, params
    for _ in range(3):
        u1, s1 = ob.update(g, s1, p1)
        u2, s2 = ad.update(g, s2, p2)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-5, atol=1e-6)


def test_onebit_adam_freezes_variance():
    rng = np.random.default_rng(3)
    params = {"w": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}
    ob = onebit_adam(learning_rate=0.1, freeze_step=2)
    s = ob.init(params)
    g1 = {"w": jnp.ones((8,), jnp.float32)}
    g2 = {"w": jnp.full((8,), 100.0, jnp.float32)}
    _, s = ob.update(g1, s, params)
    _, s = ob.update(g1, s, params)
    v_frozen = np.asarray(s.v["w"]).copy()
    _, s = ob.update(g2, s, params)       # past freeze_step
    np.testing.assert_allclose(np.asarray(s.v["w"]), v_frozen)


def test_onebit_lamb_matches_lamb_during_warmup():
    """During warmup 1-bit LAMB is exact LAMB (same trust-ratio clipping)."""
    import optax
    from deepspeed_tpu.runtime.fp16.onebit.lamb import onebit_lamb
    rng = np.random.default_rng(4)
    params = {"w": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    g = {"w": jnp.asarray(rng.normal(size=(16,)) * 0.1, jnp.float32)}
    ob = onebit_lamb(learning_rate=0.01, freeze_step=100)
    ref = optax.lamb(0.01)
    s1, s2 = ob.init(params), ref.init(params)
    p1, p2 = params, params
    for _ in range(3):
        u1, s1 = ob.update(g, s1, p1)
        u2, s2 = ref.update(g, s2, p2)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    # same algorithm family: both apply trust-ratio-scaled adam updates; the
    # directions must agree (optax.lamb has no coeff clipping, so exact
    # equality is not the contract — cosine similarity is)
    d1 = np.asarray(p1["w"]) - np.asarray(params["w"])
    d2 = np.asarray(p2["w"]) - np.asarray(params["w"])
    cos = d1 @ d2 / (np.linalg.norm(d1) * np.linalg.norm(d2))
    assert cos > 0.999, cos


def test_onebit_lamb_freezes_variance_and_scales_coeff():
    from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLambState, \
        onebit_lamb
    rng = np.random.default_rng(5)
    params = {"w": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}
    ob = onebit_lamb(learning_rate=0.01, freeze_step=2, factor_threshold=0.5)
    s = ob.init(params)
    g1 = {"w": jnp.ones((8,), jnp.float32) * 0.1}
    g2 = {"w": jnp.full((8,), 10.0, jnp.float32)}
    _, s = ob.update(g1, s, params)
    _, s = ob.update(g1, s, params)
    v_frozen = np.asarray(s.v["w"]).copy()
    cf_frozen = float(s.coeff_freeze["w"])
    u, s = ob.update(g2, s, params)       # past freeze_step
    # frozen variance unchanged; coeff_freeze EMA stops
    np.testing.assert_allclose(np.asarray(s.v["w"]), v_frozen)
    assert float(s.coeff_freeze["w"]) == cf_frozen
    # the fresh variance moved (absorbed the reconstructed big grad), and the
    # rate-limited factor departed from 1.0 toward factor_min
    assert float(np.max(np.asarray(s.v_fresh["w"]))) > float(
        np.max(v_frozen))
    assert float(s.last_factor["w"]) < 1.0
    assert np.all(np.isfinite(np.asarray(u["w"])))


def test_onebit_lamb_compressed_momentum_exchange(devices8):
    """Past freeze_step with an axis name, the momentum travels through the
    compressed all-reduce: states stay finite, the error-feedback residual
    becomes non-zero, and the variance stays frozen."""
    from deepspeed_tpu.runtime.fp16.onebit.lamb import onebit_lamb
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rng = np.random.default_rng(6)
    params = {"w": jnp.asarray(rng.normal(size=(64,)), jnp.float32)}
    local_g = rng.normal(size=(8, 64)).astype(np.float32)
    gsh = jax.device_put(jnp.asarray(local_g),
                         NamedSharding(mesh, P("dp", None)))
    ob = onebit_lamb(learning_rate=0.01, freeze_step=2, axis_name="dp",
                     axis_size=8)

    def body(g):
        g = {"w": g[0]}
        s = ob.init(params)
        p = params

        def step(carry, _):
            p, s = carry
            u, s = ob.update(g, s, p)
            import optax
            return (optax.apply_updates(p, u), s), None

        (p, s), _ = jax.lax.scan(step, (p, s), None, length=4)  # crosses 2
        return (p["w"][None], s.v["w"][None], s.error["w"][None],
                jnp.reshape(s.count, (1,)))

    p, v, err, count = shard_map(
        body, mesh=mesh, in_specs=P("dp", None),
        out_specs=(P(None, None), P(None, None), P("dp", None), P(None)),
        check_vma=False)(gsh)
    assert int(count[0]) == 4
    assert np.all(np.isfinite(np.asarray(p)))
    # the frozen phase ran the compressed exchange: worker residual non-zero
    assert float(np.abs(np.asarray(err)).max()) > 0


def test_engine_accepts_onebit_lamb(devices8):
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            optimizer={"type": "OneBitLamb",
                       "params": {"lr": 1e-3, "freeze_step": 10}}))
    b = random_batches(1, batch_size=8, seed=0)[0]
    loss = engine.train_batch(batch={"input_ids": b["input_ids"][None]})
    assert np.isfinite(float(loss))


def test_engine_accepts_onebit_adam(devices8):
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            optimizer={"type": "OneBitAdam",
                       "params": {"lr": 1e-3, "freeze_step": 10}}))
    b = random_batches(1, batch_size=8, seed=0)[0]
    loss = engine.train_batch(batch={"input_ids": b["input_ids"][None]})
    assert np.isfinite(float(loss))
