"""Multi-node launch command builders (reference: deepspeed/launcher/
multinode_runner.py — PDSH :51, OpenMPI :107, MPICH :160, IMPI :231,
Slurm :313, MVAPICH :361).

On TPU, one process runs per host (JAX single-controller SPMD), so commands
launch the user script once per host with the coordination env
(COORDINATOR_ADDRESS / NPROC / PROCESS_ID) instead of one process per
accelerator.  Command construction is pure and unit-testable, exactly like the
reference's tests (tests/unit/launcher/test_multinode_runner.py).
"""
import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info: Dict[str, int]):
        self.args = args
        self.world_info = world_info            # host -> slot count
        self.user_script = args.user_script
        self.user_arguments = list(args.user_args)
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, var: str):
        self.exports[key.strip()] = var.strip()

    @property
    def hosts(self) -> List[str]:
        return list(self.world_info.keys())

    @property
    def num_nodes(self) -> int:
        return len(self.world_info)

    @property
    def master_addr(self) -> str:
        """User-supplied --master_addr wins; default is the first host."""
        return getattr(self.args, "master_addr", "") or self.hosts[0]

    def launch_module_args(self, node_rank: str = "auto") -> List[str]:
        """The per-node ``launcher.launch`` invocation that exports the JAX
        coordination env (COORDINATOR_ADDRESS/NPROC/PROCESS_ID) before the
        user script — every backend routes through it so multi-node jobs
        rendezvous instead of running N independent single-host jobs."""
        cmd = [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--coordinator_address={self.master_addr}:{self.args.master_port}",
            f"--nnodes={self.num_nodes}",
            f"--node_rank={node_rank}",
        ]
        if getattr(self.args, "module", False):
            cmd.append("--module")
        if getattr(self.args, "no_python", False):
            cmd.append("--no_python")
        return cmd + [self.user_script] + self.user_arguments

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        ...

    def backend_exists(self) -> bool:
        return True

    @property
    def name(self) -> str:
        return self.__class__.__name__.replace("Runner", "").lower()


class PDSHRunner(MultiNodeRunner):
    """reference :51"""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(self.hosts)
        # pdsh interpolates the command through a remote shell: quote values
        exports = "".join(f"export {k}={shlex.quote(v)}; " for k, v in
                          sorted(self.exports.items()))
        # each host runs launch.py once with its PROCESS_ID derived from %n;
        # script/args pass through the remote shell, so quote each word
        flags = ""
        if getattr(self.args, "module", False):
            flags += "--module "
        if getattr(self.args, "no_python", False):
            flags += "--no_python "
        user = " ".join(shlex.quote(w) for w in
                        [self.user_script] + self.user_arguments)
        cmd = [
            "pdsh", "-S", "-f", "1024", "-w", hosts,
            exports + f"cd {shlex.quote(os.path.abspath('.'))}; "
            f"{sys.executable} -m deepspeed_tpu.launcher.launch "
            f"--coordinator_address={self.master_addr}:{self.args.master_port} "
            f"--nnodes={self.num_nodes} "
            f"--node_rank=%n "
            + flags + user,
        ]
        return cmd


class OpenMPIRunner(MultiNodeRunner):
    """reference :107"""

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        total_procs = self.num_nodes
        # --host (not the raw hostfile) so --include/--exclude/--num_nodes
        # filtering applied by runner.main is honoured
        host_list = ",".join(f"{h}:1" for h in self.hosts)
        cmd = [
            "mpirun", "-n", f"{total_procs}", "--npernode", "1",
            "--host", host_list,
            "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include", "eth0",
        ]
        for k, v in sorted(self.exports.items()):
            cmd += ["-x", f"{k}={v}"]
        cmd += self.launch_module_args(node_rank="auto")
        return cmd


class MPICHRunner(MultiNodeRunner):
    """reference :160"""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        cmd = ["mpirun", "-n", f"{self.num_nodes}", "-ppn", "1",
               "-hosts", ",".join(self.hosts)]
        for k, v in sorted(self.exports.items()):
            cmd += ["-genv", k, v]
        cmd += self.launch_module_args(node_rank="auto")
        return cmd


class IMPIRunner(MultiNodeRunner):
    """reference :231"""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        cmd = ["mpirun", "-ppn", "1", "-hosts", ",".join(self.hosts)]
        for k, v in sorted(self.exports.items()):
            cmd += ["-genv", k, v]
        cmd += self.launch_module_args(node_rank="auto")
        return cmd


class SlurmRunner(MultiNodeRunner):
    """reference :313"""

    def backend_exists(self) -> bool:
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment, active_resources):
        cmd = ["srun", "-n", f"{self.num_nodes}", "--ntasks-per-node=1"]
        if getattr(self.args, "comment", ""):
            cmd += ["--comment", self.args.comment]
        # srun inherits the submitting environment with --export=ALL; set the
        # exports there instead of the comma-separated --export list, which
        # cannot carry values containing spaces or commas (XLA_FLAGS does)
        environment.update(self.exports)
        cmd += ["--export=ALL"]
        cmd += self.launch_module_args(node_rank="auto")
        return cmd


class GcloudTPURunner(MultiNodeRunner):
    """TPU-native addition: launch across a TPU pod's hosts with
    ``gcloud compute tpus tpu-vm ssh --worker=all``."""

    def backend_exists(self) -> bool:
        return shutil.which("gcloud") is not None

    def get_cmd(self, environment, active_resources):
        tpu_name = getattr(self.args, "tpu_name", "tpu")
        zone = getattr(self.args, "zone", "")
        # the --command string runs through the remote shell: quote values
        exports = "".join(f"export {k}={shlex.quote(v)}; " for k, v in
                          sorted(self.exports.items()))
        user = " ".join(shlex.quote(w) for w in
                        [self.user_script] + self.user_arguments)
        if getattr(self.args, "no_python", False):
            interp = ""
        elif getattr(self.args, "module", False):
            interp = f"{sys.executable} -u -m "
        else:
            interp = f"{sys.executable} -u "
        inner = (exports + f"cd {shlex.quote(os.path.abspath('.'))}; "
                 + interp + user)
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
               "--worker=all", "--command", inner]
        if zone:
            cmd += ["--zone", zone]
        return cmd
