"""Diffusion UNet through the Model protocol (reference capability:
model_implementations/diffusers/{unet,vae,clip_encoder}.py:1 + the
csrc/spatial NHWC kernels).

COVERAGE.md round 4 scoped the reference's diffusers *wrappers* out (they
are torch-pipeline glue for fp16 casts + CUDA-graph capture — properties
every jitted JAX model gets from ``jit``), with the written claim that a
diffusion model "plugs in with no framework changes".  This module
proves that claim with a DDPM-style UNet2D built TPU-native:

- NHWC layout end to end (TPU convs want channels minor; the reference's
  csrc/spatial bias-adds exist to repair NCHW torch layouts — nothing to
  port);
- a mid-stack of spatial self-attention transformer blocks stored as the
  stacked ``params["blocks"]`` subtree, so the SAME engine machinery that
  serves LMs applies unchanged: int8 weight-only serving quantizes the
  stack, TP logical specs shard it Megatron-style, ZeRO shards the rest;
- the denoising-MSE ``loss_fn`` makes ``deepspeed_tpu.initialize`` train
  it like any other model (timestep sampling + noising inside the jitted
  step, rng threaded by the engine).
"""
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.model import Model, maybe_stream, resolve_size


@dataclass(frozen=True)
class UNetConfig:
    image_size: int = 32
    in_channels: int = 3
    base_channels: int = 64
    channel_mult: tuple = (1, 2)      # one downsample between stages
    num_mid_blocks: int = 2           # stacked attention blocks at the mid
    num_heads: int = 4
    time_dim: int = 128
    diffusion_steps: int = 1000
    dtype: str = "float32"
    group_norm_groups: int = 8

    @property
    def mid_channels(self) -> int:
        return self.base_channels * self.channel_mult[-1]


UNET_SIZES = {
    "tiny": dict(image_size=8, base_channels=16, num_mid_blocks=2,
                 num_heads=2, time_dim=32, group_norm_groups=4),
    "small": dict(image_size=32, base_channels=64, num_mid_blocks=2),
    "base": dict(image_size=64, base_channels=128, num_mid_blocks=4,
                 num_heads=8, time_dim=512),
}


# ------------------------------------------------------------------- layers
def _conv(x, w, b):
    """NHWC 3x3 same conv: w [3, 3, Cin, Cout]."""
    y = lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + b.astype(x.dtype)


def _group_norm(x, scale, bias, groups, eps=1e-5):
    B, H, W, C = x.shape
    g = x.astype(jnp.float32).reshape(B, H, W, groups, C // groups)
    mu = g.mean(axis=(1, 2, 4), keepdims=True)
    var = ((g - mu) ** 2).mean(axis=(1, 2, 4), keepdims=True)
    g = (g - mu) * lax.rsqrt(var + eps)
    return (g.reshape(B, H, W, C) * scale + bias).astype(x.dtype)


def _res_block(x, p, temb, groups):
    """GroupNorm -> silu -> conv, twice, with a timestep shift injected
    between, plus the residual (1x1 when channels change)."""
    h = _group_norm(x, p["n1_s"], p["n1_b"], groups)
    h = _conv(jax.nn.silu(h), p["c1_w"], p["c1_b"])
    h = h + (temb @ p["t_w"].astype(h.dtype)
             + p["t_b"].astype(h.dtype))[:, None, None, :]
    h = _group_norm(h, p["n2_s"], p["n2_b"], groups)
    h = _conv(jax.nn.silu(h), p["c2_w"], p["c2_b"])
    if "skip_w" in p:
        x = jnp.einsum("bhwc,cd->bhwd", x, p["skip_w"].astype(x.dtype))
    return x + h


def _attn_block(x_tokens, layer, cfg: UNetConfig):
    """One mid transformer block over spatial tokens [B, HW, C] — the
    Megatron shape: column-parallel QKV/MLP-in, row-parallel proj/out."""
    B, T, C = x_tokens.shape
    Hn = cfg.num_heads
    hd = C // Hn
    h = _ln(x_tokens, layer["ln1_s"], layer["ln1_b"])
    qkv = h @ layer["qkv_w"].astype(h.dtype) + layer["qkv_b"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, Hn, hd)
    k = k.reshape(B, T, Hn, hd)
    v = v.reshape(B, T, Hn, hd)
    # diffusion self-attention is BIdirectional (no causal mask); spatial
    # T is small (HW tokens at the mid resolution) — the plain einsum is
    # the right tool, XLA fuses the chain
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    probs = jax.nn.softmax(scores * (hd ** -0.5), axis=-1).astype(v.dtype)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, C)
    x_tokens = x_tokens + (attn @ layer["proj_w"].astype(h.dtype)
                           + layer["proj_b"].astype(h.dtype))
    h = _ln(x_tokens, layer["ln2_s"], layer["ln2_b"])
    h = jax.nn.gelu(h @ layer["mlp_in_w"].astype(h.dtype)
                    + layer["mlp_in_b"].astype(h.dtype))
    return x_tokens + (h @ layer["mlp_out_w"].astype(h.dtype)
                       + layer["mlp_out_b"].astype(h.dtype))


def _ln(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _timestep_embedding(t, dim):
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half, dtype=jnp.float32)
                    / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


# ------------------------------------------------------------------- params
def init_params(config: UNetConfig, rng) -> dict:
    C0 = config.base_channels
    C1 = config.mid_channels
    Cin = config.in_channels
    TD = config.time_dim
    L = config.num_mid_blocks
    k = iter(jax.random.split(rng, 64))
    n = lambda *s: jax.random.normal(next(k), s, jnp.float32)

    def conv_p(cin, cout, scale=0.02):
        return {"w": n(3, 3, cin, cout) * scale, "b": jnp.zeros((cout,))}

    def res_p(cin, cout):
        p = {"n1_s": jnp.ones((cin,)), "n1_b": jnp.zeros((cin,)),
             "c1_w": n(3, 3, cin, cout) * 0.02, "c1_b": jnp.zeros((cout,)),
             "t_w": n(TD, cout) * 0.02, "t_b": jnp.zeros((cout,)),
             "n2_s": jnp.ones((cout,)), "n2_b": jnp.zeros((cout,)),
             "c2_w": n(3, 3, cout, cout) * 0.02, "c2_b": jnp.zeros((cout,))}
        if cin != cout:
            p["skip_w"] = n(cin, cout) * 0.02
        return p

    blocks = {
        "ln1_s": jnp.ones((L, C1)), "ln1_b": jnp.zeros((L, C1)),
        "qkv_w": n(L, C1, 3 * C1) * 0.02, "qkv_b": jnp.zeros((L, 3 * C1)),
        "proj_w": n(L, C1, C1) * 0.02, "proj_b": jnp.zeros((L, C1)),
        "ln2_s": jnp.ones((L, C1)), "ln2_b": jnp.zeros((L, C1)),
        "mlp_in_w": n(L, C1, 4 * C1) * 0.02,
        "mlp_in_b": jnp.zeros((L, 4 * C1)),
        "mlp_out_w": n(L, 4 * C1, C1) * 0.02,
        "mlp_out_b": jnp.zeros((L, C1)),
    }
    return {
        "time_mlp_in": n(TD, TD) * 0.02, "time_mlp_in_b": jnp.zeros((TD,)),
        "time_mlp_out": n(TD, TD) * 0.02, "time_mlp_out_b": jnp.zeros((TD,)),
        "stem": conv_p(Cin, C0),
        "down1": res_p(C0, C0),
        "down_sample": conv_p(C0, C1),     # stride-2 applied in forward
        "down2": res_p(C1, C1),
        "blocks": blocks,
        "up1": res_p(2 * C1, C1),
        "up2": res_p(C1 + C0, C0),
        "head_n_s": jnp.ones((C0,)), "head_n_b": jnp.zeros((C0,)),
        "head": conv_p(C0, Cin, scale=1e-3),
    }


def logical_specs(config: UNetConfig) -> dict:
    """Megatron TP on the mid transformer stack; conv stages replicate
    (their channel counts are small next to the mid stack)."""
    # abstract init: structure only, no tensors materialize
    shapes = jax.eval_shape(partial(init_params, config),
                            jax.random.PRNGKey(0))
    none = lambda p: jax.tree.map(lambda _: P(), p)
    return {
        "time_mlp_in": P(), "time_mlp_in_b": P(),
        "time_mlp_out": P(), "time_mlp_out_b": P(),
        "stem": {"w": P(), "b": P()},
        "down1": none(shapes["down1"]),
        "down_sample": {"w": P(), "b": P()},
        "down2": none(shapes["down2"]),
        "blocks": {
            "ln1_s": P(), "ln1_b": P(),
            "qkv_w": P(None, None, "model"), "qkv_b": P(None, "model"),
            "proj_w": P(None, "model", None), "proj_b": P(),
            "ln2_s": P(), "ln2_b": P(),
            "mlp_in_w": P(None, None, "model"), "mlp_in_b": P(None, "model"),
            "mlp_out_w": P(None, "model", None), "mlp_out_b": P(),
        },
        "up1": none(shapes["up1"]),
        "up2": none(shapes["up2"]),
        "head_n_s": P(), "head_n_b": P(),
        "head": {"w": P(), "b": P()},
    }


# ------------------------------------------------------------------ forward
def forward(params, batch, config: UNetConfig, rng=None):
    """eps prediction: batch {"images" [B,H,W,C] noised, "timesteps" [B]}
    -> eps_hat [B,H,W,C]."""
    dtype = jnp.dtype(config.dtype)
    x = batch["images"].astype(dtype)
    t = batch["timesteps"]
    g = config.group_norm_groups

    temb = _timestep_embedding(t, config.time_dim).astype(dtype)
    temb = jax.nn.silu(temb @ params["time_mlp_in"].astype(dtype)
                       + params["time_mlp_in_b"].astype(dtype))
    temb = (temb @ params["time_mlp_out"].astype(dtype)
            + params["time_mlp_out_b"].astype(dtype))

    h0 = _conv(x, params["stem"]["w"], params["stem"]["b"])
    h0 = _res_block(h0, params["down1"], temb, g)
    # stride-2 downsample into the mid width
    h1 = lax.conv_general_dilated(
        h0, params["down_sample"]["w"].astype(dtype), (2, 2), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")
    ) + params["down_sample"]["b"].astype(dtype)
    h1 = _res_block(h1, params["down2"], temb, g)

    # mid: spatial self-attention transformer stack (lax.scan over the
    # stacked blocks — the LM machinery's layout)
    B, Hh, Ww, C1 = h1.shape
    tokens = h1.reshape(B, Hh * Ww, C1)

    def body(carry, layer):
        layer = maybe_stream(layer)
        return _attn_block(carry, layer, config), None

    tokens, _ = lax.scan(body, tokens, params["blocks"])
    hm = tokens.reshape(B, Hh, Ww, C1)

    u = _res_block(jnp.concatenate([hm, h1], axis=-1), params["up1"],
                   temb, g)
    # nearest-neighbour upsample back to the stem resolution
    u = jnp.repeat(jnp.repeat(u, 2, axis=1), 2, axis=2)
    u = _res_block(jnp.concatenate([u, h0], axis=-1), params["up2"],
                   temb, g)
    u = jax.nn.silu(_group_norm(u, params["head_n_s"], params["head_n_b"],
                                g))
    return _conv(u, params["head"]["w"], params["head"]["b"])


def ddpm_loss(params, batch, config: UNetConfig, rng=None):
    """Denoising objective: sample t and eps inside the jitted step, noise
    the clean images with the DDPM cosine-free linear schedule, and
    regress the predicted eps (Ho et al. 2020 — public formulation)."""
    clean = batch["images"]
    B = clean.shape[0]
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    t_key, e_key = jax.random.split(jax.random.fold_in(rng, 1))
    t = jax.random.randint(t_key, (B,), 0, config.diffusion_steps)
    eps = jax.random.normal(e_key, clean.shape, jnp.float32)
    beta = jnp.linspace(1e-4, 0.02, config.diffusion_steps)
    abar = jnp.cumprod(1.0 - beta)[t][:, None, None, None]
    noised = (jnp.sqrt(abar) * clean.astype(jnp.float32)
              + jnp.sqrt(1.0 - abar) * eps)
    pred = forward(params, {"images": noised, "timesteps": t}, config, rng)
    return jnp.mean((pred.astype(jnp.float32) - eps) ** 2)


def count_params(config: UNetConfig) -> int:
    p = jax.eval_shape(partial(init_params, config), jax.random.PRNGKey(0))
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(p))


def unet_model(size: str = "small", **overrides) -> Model:
    cfg_kwargs = resolve_size(UNET_SIZES, size, "unet")
    cfg_kwargs.update(overrides)
    config = UNetConfig(**cfg_kwargs)
    if config.mid_channels % config.num_heads:
        raise ValueError(
            f"num_heads ({config.num_heads}) must divide the mid channel "
            f"count ({config.mid_channels})")
    n_params = count_params(config)
    return Model(
        config=config,
        init_fn=partial(init_params, config),
        apply_fn=lambda p, b, rng=None: forward(p, b, config, rng),
        loss_fn=lambda p, b, rng=None: ddpm_loss(p, b, config, rng),
        logical_specs=logical_specs(config),
        meta={"name": f"unet-{size}", "n_params": n_params,
              "modality": "diffusion"},
    )
