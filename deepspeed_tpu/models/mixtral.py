"""Mixtral-style MoE decoder: Llama blocks with top-k-routed expert SwiGLU
FFNs, expert-parallel over the ``expert`` mesh axis (BASELINE.md config 5:
Mixtral-8x7B EP + Ulysses SP).
"""
from dataclasses import dataclass
from functools import partial

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.model import Model, qdot, resolve_size
from deepspeed_tpu.models.llama import _rms_norm, rope
from deepspeed_tpu.moe.layer import MoEConfig, moe_layer
from deepspeed_tpu.moe.sharded_moe import topkgating
from deepspeed_tpu.ops.attention import causal_attention


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    d_model: int = 4096
    d_ff: int = 14336
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    #: None (default) = drop-free at eval/serving (capacity >= all tokens
    #: on one expert, the HF serving semantic — keeps the cached decode,
    #: the prefill, and the no-cache oracle token-identical regardless of
    #: router skew).  Set a number to cap eval capacity (cheaper dispatch
    #: for long prefills, at the cost of potential drops).
    eval_capacity_factor: "float | None" = None
    #: expert dispatch formulation (moe/layer.py dispatch_mode): "auto"
    #: (default — einsum when training, megablocks-style grouped GEMM at
    #: eval/serving), "einsum", or "grouped".  Grouped serving consumes
    #: int8 expert stacks in place through the fused-dequant grouped
    #: kernel (ops/pallas/grouped_gemm.py) instead of the per-expert
    #: residual-dequant fallback (ISSUE 8).
    moe_dispatch: str = "auto"
    aux_loss_coef: float = 0.01
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: str = "nothing"
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def moe(self) -> MoEConfig:
        eval_cf = (self.eval_capacity_factor
                   if self.eval_capacity_factor is not None
                   else self.num_experts / self.top_k)
        return MoEConfig(d_model=self.d_model, d_ff=self.d_ff,
                         num_experts=self.num_experts, top_k=self.top_k,
                         capacity_factor=self.capacity_factor,
                         eval_capacity_factor=eval_cf,
                         aux_loss_coef=self.aux_loss_coef,
                         activation="silu_glu",
                         dispatch_mode=self.moe_dispatch)


MIXTRAL_SIZES = {
    "tiny": dict(vocab_size=256, max_seq_len=128, num_layers=2, num_heads=4,
                 num_kv_heads=2, d_model=32, d_ff=64, num_experts=4, top_k=2),
    # single-chip bench config (~0.8B total / ~0.3B active): full MoE
    # state (bf16 params + fp32 masters/moments) fits one 16 GB chip
    "1b-moe": dict(vocab_size=32000, max_seq_len=2048, num_layers=8,
                   num_heads=16, num_kv_heads=8, d_model=1024, d_ff=3584,
                   num_experts=8, top_k=2),
    "8x7b": dict(),
}


def init_params(config: MixtralConfig, rng) -> dict:
    D, V, L = config.d_model, config.vocab_size, config.num_layers
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    E, F = config.num_experts, config.d_ff
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    res_std = std / (2 * L) ** 0.5
    norm = partial(jax.random.normal, dtype=jnp.float32)
    return {
        "wte": norm(next(k), (V, D)) * std,
        "blocks": {
            "attn_norm": jnp.ones((L, D)),
            "wq": norm(next(k), (L, D, H * hd)) * std,
            "wk": norm(next(k), (L, D, KV * hd)) * std,
            "wv": norm(next(k), (L, D, KV * hd)) * std,
            "wo": norm(next(k), (L, H * hd, D)) * res_std,
            "mlp_norm": jnp.ones((L, D)),
            "moe": {
                "router": norm(next(k), (L, D, E)) * std,
                "w_gate": norm(next(k), (L, E, D, F)) * std,
                "w_in": norm(next(k), (L, E, D, F)) * std,
                "w_out": norm(next(k), (L, E, F, D)) * res_std,
            },
        },
        "final_norm": jnp.ones((D,)),
        "lm_head": norm(next(k), (D, V)) * std,
    }


def logical_specs(config: MixtralConfig) -> dict:
    return {
        "wte": P("model", None),
        "blocks": {
            "attn_norm": P(),
            "wq": P(None, None, "model"),
            "wk": P(None, None, "model"),
            "wv": P(None, None, "model"),
            "wo": P(None, "model", None),
            "mlp_norm": P(),
            "moe": {
                "router": P(),
                "w_gate": P(None, "expert", None, "model"),
                "w_in": P(None, "expert", None, "model"),
                "w_out": P(None, "expert", "model", None),
            },
        },
        "final_norm": P(),
        "lm_head": P(None, "model"),
    }


def _qkv(x, layer, config: MixtralConfig, positions=None):
    """RMSNorm + QKV + rotary; kv heads NOT repeated (compact caches)."""
    B, S, D = x.shape
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    h = _rms_norm(x, layer["attn_norm"], config.rms_norm_eps)
    q = rope(qdot(h, layer["wq"]).reshape(B, S, H, hd),
             config.rope_theta, positions)
    kk = rope(qdot(h, layer["wk"]).reshape(B, S, KV, hd),
              config.rope_theta, positions)
    v = qdot(h, layer["wv"]).reshape(B, S, KV, hd)
    return q, kk, v


def _moe_finish(x, attn_flat, layer, config: MixtralConfig, train: bool,
                rng=None):
    """Attention output projection + residual + routed-expert FFN."""
    x = x + qdot(attn_flat, layer["wo"])
    h = _rms_norm(x, layer["mlp_norm"], config.rms_norm_eps)
    moe_out, aux = moe_layer(layer["moe"], h, config.moe, train=train,
                             rng=rng)
    return x + moe_out, aux


def _block(carry, layer, config: MixtralConfig, train: bool, rng=None,
           segment_ids=None):
    x = carry
    B, S, D = x.shape
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    q, kk, v = _qkv(x, layer, config)
    attn = causal_attention(q, kk, v, impl=config.attention_impl,
                            segment_ids=segment_ids)
    attn = jax.ad_checkpoint.checkpoint_name(attn, "attn_out")
    return _moe_finish(x, attn.reshape(B, S, H * hd), layer, config,
                       train, rng)


def forward_with_aux(params, batch, config: MixtralConfig, train: bool = True,
                     rng=None):
    tokens = batch["input_ids"]
    dtype = jnp.dtype(config.dtype)
    x = params["wte"].astype(dtype)[tokens]
    seg = batch.get("segment_ids") if isinstance(batch, dict) else None
    # stream-inside-remat (see models/model.py maybe_stream)
    def block_fn(x, layer):
        from deepspeed_tpu.models.model import maybe_stream
        return _block(x, maybe_stream(layer), config, train=train, rng=rng,
                      segment_ids=seg)
    if config.remat:
        from deepspeed_tpu.models.gpt2 import remat_policy
        block_fn = jax.checkpoint(
            block_fn, policy=remat_policy(config.remat_policy))
    x, aux = lax.scan(block_fn, x, params["blocks"])
    x = _rms_norm(x, params["final_norm"], config.rms_norm_eps)
    return x @ params["lm_head"].astype(dtype), jnp.sum(aux)


# --------------------------------------------------------------------- decode
# MoE serving path (reference capability:
# ops/transformer/inference/moe_inference.py + inference/engine.py:230 EP
# groups): the shared rotary-GQA cache scaffold (models/serving.py) with
# the routed-expert FFN as the post-attention block — drop-free at eval by
# default, EP-sharded when the mesh has a wide expert axis.

def _serving_fns(config: MixtralConfig):
    from deepspeed_tpu.models import serving

    def embed_fn(params, tokens):
        return params["wte"].astype(jnp.dtype(config.dtype))[tokens]

    def qkv_fn(x, layer, positions):
        return _qkv(x, layer, config, positions)

    def finish_fn(x, attn_flat, layer):
        out, _ = _moe_finish(x, attn_flat, layer, config, train=False)
        return out

    def head_fn(params, x):
        x = _rms_norm(x, params["final_norm"], config.rms_norm_eps)
        return x @ params["lm_head"].astype(jnp.dtype(config.dtype))

    # fused per-layer megakernel wiring (ISSUE 12): the kernel fuses
    # RMSNorm + QKV + rotary + GQA decode attention + attn-out
    # (mlp="none"); the routed-expert FFN stays OUTSIDE as the
    # ``moe_tail_fn`` so it keeps riding the grouped-GEMM slot kernels
    # (ISSUE 8) — one megakernel launch + the expert dispatch per layer
    from deepspeed_tpu.ops.pallas.fused_decode import FusedLayerSpec
    fused_spec = FusedLayerSpec(
        num_heads=config.num_heads, num_kv_heads=config.num_kv_heads,
        head_dim=config.head_dim, d_model=config.d_model,
        norm="rms", eps=config.rms_norm_eps, qkv="split",
        qkv_bias=False, out_bias=False, mlp="none",
        rotary_dims=config.head_dim, rope_theta=config.rope_theta)

    def fused_weights(layer):
        return {"n1_s": layer["attn_norm"], "wq": layer["wq"],
                "wk": layer["wk"], "wv": layer["wv"], "wo": layer["wo"]}

    def moe_tail(x, layer):
        h = _rms_norm(x, layer["mlp_norm"], config.rms_norm_eps)
        moe_out, _ = moe_layer(layer["moe"], h, config.moe, train=False)
        return x + moe_out

    def init_cache_fn(bs, max_len, dtype=None):
        return serving.init_cache(config.num_layers, config.num_kv_heads,
                                  config.head_dim, bs, max_len, dtype,
                                  config.dtype)

    def prefill_fn(p, b, c):
        return serving.prefill(
            p, b, c, embed_fn=embed_fn, qkv_fn=qkv_fn, finish_fn=finish_fn,
            head_fn=head_fn, num_heads=config.num_heads,
            num_kv_heads=config.num_kv_heads,
            attention_impl=config.attention_impl)

    def decode_fn(p, t, c, l):
        return serving.decode_step(
            p, t, c, l, embed_fn=embed_fn, qkv_fn=qkv_fn,
            finish_fn=finish_fn, head_fn=head_fn,
            num_heads=config.num_heads,
            moe_grouped=serving.moe_dispatch_grouped(config.moe),
            fused_spec=fused_spec, fused_weights_fn=fused_weights,
            moe_tail_fn=moe_tail)

    def verify_fn(p, t, c, l):
        return serving.verify_window(
            p, t, c, l, embed_fn=embed_fn, qkv_fn=qkv_fn,
            finish_fn=finish_fn, head_fn=head_fn,
            num_heads=config.num_heads,
            moe_grouped=serving.moe_dispatch_grouped(config.moe),
            fused_spec=fused_spec, fused_weights_fn=fused_weights,
            moe_tail_fn=moe_tail)

    return init_cache_fn, prefill_fn, decode_fn, verify_fn


def count_params(config: MixtralConfig) -> int:
    import numpy as np
    shapes = jax.eval_shape(partial(init_params, config), jax.random.PRNGKey(0))
    return int(sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes)))


def mixtral_model(size: str = "8x7b", **overrides) -> Model:
    import optax
    cfg_kwargs = resolve_size(MIXTRAL_SIZES, size, "mixtral")
    cfg_kwargs.update(overrides)
    config = MixtralConfig(**cfg_kwargs)
    n_params = count_params(config)
    # active params per token ≈ dense part + top_k/num_experts of experts
    active = n_params - (1 - config.top_k / config.num_experts) * (
        3 * config.num_layers * config.num_experts * config.d_model * config.d_ff)

    def loss_fn(params, batch, rng=None):
        tokens = batch["input_ids"]
        logits, aux = forward_with_aux(params, batch, config, train=True, rng=rng)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1].astype(jnp.float32), tokens[:, 1:]).mean()
        return ce + aux

    return Model(
        config=config,
        init_fn=partial(init_params, config),
        apply_fn=lambda p, b, rng=None: forward_with_aux(
            p, b, config, train=False, rng=rng)[0],
        loss_fn=loss_fn,
        logical_specs=logical_specs(config),
        flops_per_token=6.0 * active,
        meta={"name": f"mixtral-{size}", "n_params": n_params,
              "active_params": active},
        **dict(zip(("init_cache_fn", "prefill_fn", "decode_fn",
                    "verify_fn"),
                   _serving_fns(config))),
    )
