"""BERT encoder + MLM (the reference's flagship kernel-benchmark model,
docs/_posts/2020-05-28-fastest-bert-training.md)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.bert import bert_model
from tests.util import base_config


def tiny_bert(**overrides):
    kw = dict(vocab_size=128, max_seq_len=32, num_layers=2, num_heads=4,
              d_model=32, dtype="float32", attention_impl="xla")
    kw.update(overrides)
    return bert_model(size="custom", **kw)


def _mlm_batch(rng, B=4, S=16, vocab=128, mask_frac=0.15):
    ids = rng.integers(0, vocab, size=(B, S)).astype(np.int32)
    labels = np.full((B, S), -100, np.int32)
    mask = rng.random((B, S)) < mask_frac
    mask[:, 0] = True                     # ≥1 masked position per row
    labels[mask] = ids[mask]
    inp = ids.copy()
    inp[mask] = 3                         # [MASK]
    return {"input_ids": inp, "labels": labels,
            "attention_mask": np.ones((B, S), np.int32)}


def test_forward_shapes_and_padding_invariance():
    """Padding tokens must not influence real positions."""
    model = tiny_bert()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(2, 16)).astype(np.int32)
    am = np.ones((2, 16), np.int32)
    am[:, 12:] = 0                        # last 4 are padding
    out1 = model.apply(params, {"input_ids": ids, "attention_mask": am})
    assert out1.shape == (2, 16, 128)
    ids2 = ids.copy()
    ids2[:, 12:] = 7                      # change padding content
    out2 = model.apply(params, {"input_ids": ids2, "attention_mask": am})
    np.testing.assert_allclose(np.asarray(out1[:, :12]),
                               np.asarray(out2[:, :12]), atol=1e-5)


def test_mlm_loss_only_counts_masked_positions():
    model = tiny_bert()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    b = _mlm_batch(rng)
    loss = float(model.loss(params, b))
    assert np.isfinite(loss) and loss > 0
    # perturbing labels at unmasked (-100) positions changes nothing
    b2 = {k: v.copy() for k, v in b.items()}
    unmasked = b2["labels"] == -100
    assert unmasked.any()
    loss2 = float(model.loss(params, b2))
    assert loss == loss2


def test_bert_trains_and_loss_decreases(devices8):
    model = tiny_bert()
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=base_config(
            train_micro_batch_size_per_gpu=8,
            optimizer={"type": "Adam", "params": {"lr": 1e-3}}))
    rng = np.random.default_rng(2)
    b = _mlm_batch(rng, B=8, S=16)
    batch = {k: v[None] for k, v in b.items()}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert losses[-1] < losses[0]         # memorises one batch


def test_bert_tp_matches_dp(devices8):
    """TP=2 sharded BERT reproduces the pure-DP loss trajectory."""
    from deepspeed_tpu.comm import reset_topology
    rng = np.random.default_rng(3)
    b = _mlm_batch(rng, B=8, S=16)
    batch = {k: v[None] for k, v in b.items()}

    def run(**mesh):
        reset_topology()
        cfg = base_config(train_micro_batch_size_per_gpu=8,
                          optimizer={"type": "Adam", "params": {"lr": 1e-3}})
        if mesh:
            cfg["mesh"] = mesh
        engine, *_ = deepspeed_tpu.initialize(model=tiny_bert(), config=cfg)
        return [float(engine.train_batch(batch=batch)) for _ in range(2)]

    dp = run()
    tp = run(model_parallel_size=2)
    np.testing.assert_allclose(dp, tp, rtol=2e-4)


def test_bert_skips_random_ltd_with_padding_mask():
    """An active LTD keep-count must not crash (or misalign) a padded
    encoder batch — BERT skips token drop when a mask is closed over."""
    from deepspeed_tpu.runtime.data_pipeline.random_ltd import ltd_scope
    model = tiny_bert()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    b = _mlm_batch(rng, B=2, S=16)
    with ltd_scope(8):
        out = model.apply(params, b, jax.random.PRNGKey(1))
    assert out.shape == (2, 16, 128)
    # without a mask the drop DOES engage (output differs from no-scope run)
    b2 = {"input_ids": b["input_ids"]}
    with ltd_scope(8):
        dropped = model.apply(params, b2, jax.random.PRNGKey(1))
    full = model.apply(params, b2, jax.random.PRNGKey(1))
    assert not np.allclose(np.asarray(dropped), np.asarray(full))
