"""Hugging Face interop: convert `transformers` checkpoints to the native
param pytrees (reference capability: DeepSpeed wraps HF modules directly
— init_inference(model=AutoModel...) + AutoTP; in the functional design
the equivalent is a weight conversion into the in-tree models, after
which every engine feature — ZeRO, TP via the hand specs, KV-cache
serving, int8 quantization — applies unchanged).

Converters accept a live `transformers` model OR its ``state_dict()``
(anything indexable by parameter name whose values have ``.numpy()`` or
are array-like).  Logits parity against transformers' own forward is
asserted in tests/test_hf_interop.py.
"""
from typing import Any, Dict, Tuple

import numpy as np


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach()
    if hasattr(t, "cpu"):
        t = t.cpu()
    if hasattr(t, "float"):
        # torch bf16/fp16 tensors refuse .numpy(); widen first (real HF
        # checkpoints load as bf16 with torch_dtype="auto")
        t = t.float()
    if hasattr(t, "numpy"):
        # copy=True: for fp32 tensors .numpy() is a zero-copy view of
        # torch-OWNED memory, and np.asarray keeps it zero-copy.  The jax
        # CPU backend can alias such host buffers into its arrays, and a
        # donated/freed aliased buffer corrupts the heap (glibc "corrupted
        # size vs. prev_size" mid-train, torch's allocator vs XLA's) —
        # every converted leaf must own its storage
        return np.array(t.numpy(), dtype=np.float32, copy=True)
    return np.asarray(t, dtype=np.float32)


def _state_dict(model_or_sd) -> Dict[str, Any]:
    if hasattr(model_or_sd, "state_dict"):
        return model_or_sd.state_dict()
    return model_or_sd


def gpt2_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF GPT2LMHeadModel (or its state_dict) -> (Model, params).

    HF's Conv1D already stores weights [in, out] — the same layout as the
    native blocks — so the mapping is a rename + per-layer stack."""
    from deepspeed_tpu.models.gpt2 import gpt2_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"transformer.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("transformer.h."))
    D = g("wte.weight").shape[1]
    cfg = dict(vocab_size=g("wte.weight").shape[0],
               max_seq_len=g("wpe.weight").shape[0],
               num_layers=n_layers, d_model=D,
               num_heads=overrides.pop("num_heads", None)
               or _gpt2_heads(model_or_sd, D))
    cfg.update(overrides)
    model = gpt2_model("custom", **cfg)
    if "lm_head.weight" in sd and not np.allclose(
            _to_np(sd["lm_head.weight"]), g("wte.weight")):
        raise ValueError(
            "gpt2_from_hf: checkpoint has an UNTIED lm_head; the native "
            "gpt2 ties the head to the embedding by construction and "
            "cannot represent it")

    def stack(fmt):
        return np.stack([g(fmt.format(i)) for i in range(n_layers)])

    params = {
        "wte": g("wte.weight"),
        "wpe": g("wpe.weight"),
        "blocks": {
            "ln1_scale": stack("h.{}.ln_1.weight"),
            "ln1_bias": stack("h.{}.ln_1.bias"),
            "qkv_w": stack("h.{}.attn.c_attn.weight"),
            "qkv_b": stack("h.{}.attn.c_attn.bias"),
            "proj_w": stack("h.{}.attn.c_proj.weight"),
            "proj_b": stack("h.{}.attn.c_proj.bias"),
            "ln2_scale": stack("h.{}.ln_2.weight"),
            "ln2_bias": stack("h.{}.ln_2.bias"),
            "mlp_in_w": stack("h.{}.mlp.c_fc.weight"),
            "mlp_in_b": stack("h.{}.mlp.c_fc.bias"),
            "mlp_out_w": stack("h.{}.mlp.c_proj.weight"),
            "mlp_out_b": stack("h.{}.mlp.c_proj.bias"),
        },
        "lnf_scale": g("ln_f.weight"),
        "lnf_bias": g("ln_f.bias"),
    }
    return model, params


def _gpt2_heads(model_or_sd, d_model: int) -> int:
    cfg = getattr(model_or_sd, "config", None)
    if cfg is not None and getattr(cfg, "n_head", None):
        return int(cfg.n_head)
    # head count is not recoverable from a bare state_dict; GPT-2 family
    # convention is hd=64
    return max(1, d_model // 64)


def bert_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF BertForMaskedLM (or its state_dict) -> (Model, params).
    torch Linear stores [out, in] — projections transpose; Q/K/V concat
    into the fused qkv matrices; the MLM decoder ties to the embedding."""
    from deepspeed_tpu.models.bert import bert_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"bert.{k}"])
    n_layers = 1 + max(int(k.split(".")[3]) for k in sd
                       if k.startswith("bert.encoder.layer."))
    hf_cfg = getattr(model_or_sd, "config", None)
    D = g("embeddings.word_embeddings.weight").shape[1]
    cfg = dict(vocab_size=g("embeddings.word_embeddings.weight").shape[0],
               max_seq_len=g("embeddings.position_embeddings.weight").shape[0],
               type_vocab_size=g(
                   "embeddings.token_type_embeddings.weight").shape[0],
               num_layers=n_layers, d_model=D,
               num_heads=(int(hf_cfg.num_attention_heads)
                          if hf_cfg is not None else max(1, D // 64)),
               # HF default act = erf gelu; gelu_new/tanh variants map to
               # the approximate form
               gelu_approximate=(
                   getattr(hf_cfg, "hidden_act", "gelu")
                   in ("gelu_new", "gelu_pytorch_tanh", "gelu_fast")
                   if hf_cfg is not None else False))
    cfg.update(overrides)
    model = bert_model("custom", **cfg)

    def lay(i, k):
        return _to_np(sd[f"bert.encoder.layer.{i}.{k}"])

    def stack(k, transpose=False):
        return np.stack([lay(i, k).T if transpose else lay(i, k)
                         for i in range(n_layers)])

    qkv_w = np.concatenate([stack("attention.self.query.weight", True),
                            stack("attention.self.key.weight", True),
                            stack("attention.self.value.weight", True)],
                           axis=-1)
    qkv_b = np.concatenate([stack("attention.self.query.bias"),
                            stack("attention.self.key.bias"),
                            stack("attention.self.value.bias")], axis=-1)
    params = {
        "wte": g("embeddings.word_embeddings.weight"),
        "wpe": g("embeddings.position_embeddings.weight"),
        "wtype": g("embeddings.token_type_embeddings.weight"),
        "emb_ln_scale": g("embeddings.LayerNorm.weight"),
        "emb_ln_bias": g("embeddings.LayerNorm.bias"),
        "blocks": {
            "qkv_w": qkv_w, "qkv_b": qkv_b,
            "proj_w": stack("attention.output.dense.weight", True),
            "proj_b": stack("attention.output.dense.bias"),
            "ln1_scale": stack("attention.output.LayerNorm.weight"),
            "ln1_bias": stack("attention.output.LayerNorm.bias"),
            "mlp_in_w": stack("intermediate.dense.weight", True),
            "mlp_in_b": stack("intermediate.dense.bias"),
            "mlp_out_w": stack("output.dense.weight", True),
            "mlp_out_b": stack("output.dense.bias"),
            "ln2_scale": stack("output.LayerNorm.weight"),
            "ln2_bias": stack("output.LayerNorm.bias"),
        },
        "mlm_dense_w": _to_np(sd["cls.predictions.transform.dense.weight"]).T,
        "mlm_dense_b": _to_np(sd["cls.predictions.transform.dense.bias"]),
        "mlm_ln_scale": _to_np(
            sd["cls.predictions.transform.LayerNorm.weight"]),
        "mlm_ln_bias": _to_np(sd["cls.predictions.transform.LayerNorm.bias"]),
        "mlm_bias": _to_np(sd["cls.predictions.bias"]),
    }
    return model, params


def llama_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF LlamaForCausalLM (or its state_dict) -> (Model, params).

    torch Linear stores [out, in]; the native layout is [in, out], so the
    projection weights transpose."""
    from deepspeed_tpu.models.llama import llama_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"model.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("model.layers."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None and not {"num_heads", "rope_theta"} <= set(overrides):
        # same reject-what-you-cannot-represent policy as the rope_scaling
        # check below: head_dim/theta are not recoverable from a bare state
        # dict, and guessed values silently corrupt every position for
        # Llama-3-family checkpoints (rope_theta=500000, hd=128)
        raise ValueError(
            "llama_from_hf: bare state_dict carries no config — pass the "
            "transformers model, or supply both num_heads= and rope_theta= "
            "overrides (and max_seq_len= if not 4096)")
    D = g("embed_tokens.weight").shape[1]
    kv_rows = g("layers.0.self_attn.k_proj.weight").shape[0]
    q_rows = g("layers.0.self_attn.q_proj.weight").shape[0]
    heads = (int(hf_cfg.num_attention_heads) if hf_cfg is not None
             else int(overrides["num_heads"]))
    hd = q_rows // heads
    cfg = dict(vocab_size=g("embed_tokens.weight").shape[0],
               num_layers=n_layers, d_model=D, num_heads=heads,
               num_kv_heads=kv_rows // hd,
               d_mlp=g("layers.0.mlp.gate_proj.weight").shape[0])
    if hf_cfg is not None:
        if getattr(hf_cfg, "rope_scaling", None):
            raise NotImplementedError(
                "llama_from_hf: checkpoint uses rope_scaling="
                f"{hf_cfg.rope_scaling!r} (Llama-3.1+ style); the native "
                "rope() applies plain theta only — converting would "
                "produce wrong logits at every position")
        cfg["rope_theta"] = float(getattr(hf_cfg, "rope_theta", 10000.0))
        cfg["rms_norm_eps"] = float(getattr(hf_cfg, "rms_norm_eps", 1e-5))
        cfg["max_seq_len"] = int(getattr(hf_cfg, "max_position_embeddings",
                                         4096))
    # biased attention projections (InternLM / attention_bias=True
    # checkpoints — reference container module_inject/containers/internlm.py)
    attn_bias = "model.layers.0.self_attn.q_proj.bias" in sd
    cfg["attn_bias"] = attn_bias
    cfg.update(overrides)
    model = llama_model("custom", **cfg)

    def stack_t(fmt):
        return np.stack([g(fmt.format(i)).T for i in range(n_layers)])

    def stack(fmt):
        return np.stack([g(fmt.format(i)) for i in range(n_layers)])

    blocks = {
        "attn_norm": stack("layers.{}.input_layernorm.weight"),
        "wq": stack_t("layers.{}.self_attn.q_proj.weight"),
        "wk": stack_t("layers.{}.self_attn.k_proj.weight"),
        "wv": stack_t("layers.{}.self_attn.v_proj.weight"),
        "wo": stack_t("layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": stack("layers.{}.post_attention_layernorm.weight"),
        "w_gate": stack_t("layers.{}.mlp.gate_proj.weight"),
        "w_up": stack_t("layers.{}.mlp.up_proj.weight"),
        "w_down": stack_t("layers.{}.mlp.down_proj.weight"),
    }
    if attn_bias:
        blocks["wq_b"] = stack("layers.{}.self_attn.q_proj.bias")
        blocks["wk_b"] = stack("layers.{}.self_attn.k_proj.bias")
        blocks["wv_b"] = stack("layers.{}.self_attn.v_proj.bias")
        blocks["wo_b"] = (
            stack("layers.{}.self_attn.o_proj.bias")
            if "model.layers.0.self_attn.o_proj.bias" in sd
            else np.zeros((n_layers, D), np.float32))
    params = {
        "wte": g("embed_tokens.weight"),
        "blocks": blocks,
        "final_norm": g("norm.weight"),
        # tied-embedding checkpoints (safetensors drops the shared tensor)
        # reuse the embedding matrix as the head
        "lm_head": _to_np(sd["lm_head.weight"]).T
        if "lm_head.weight" in sd else g("embed_tokens.weight").T,
    }
    return model, params


def internlm_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """InternLM (reference container: module_inject/containers/internlm.py:1)
    is the llama block with biased q/k/v/o projections and the same
    ``model.layers.*`` checkpoint naming — ``llama_from_hf`` detects and
    loads the biases, so this entry point is the documented alias."""
    return llama_from_hf(model_or_sd, **overrides)


def mixtral_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF MixtralForCausalLM (or its state_dict) -> (Model, params).

    Expert mapping (HF MixtralSparseMoeBlock): w1 = gated (silu) proj ->
    ``w_gate``, w3 = linear up proj -> ``w_in``, w2 = down proj ->
    ``w_out``; ``gate.weight`` [E, D] -> router [D, E]."""
    from deepspeed_tpu.models.mixtral import mixtral_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"model.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("model.layers."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None and not (
            {"num_heads", "rope_theta", "top_k"} <= set(overrides)):
        # head count, theta AND experts-per-token are unrecoverable from
        # bare weights; a guessed top_k silently mis-routes every token
        raise ValueError(
            "mixtral_from_hf: bare state_dict carries no config — pass the "
            "transformers model, or supply num_heads=, rope_theta= and "
            "top_k= overrides")
    D = g("embed_tokens.weight").shape[1]
    q_rows = g("layers.0.self_attn.q_proj.weight").shape[0]
    kv_rows = g("layers.0.self_attn.k_proj.weight").shape[0]
    n_experts = 1 + max(
        int(k.split(".")[5]) for k in sd
        if ".block_sparse_moe.experts." in k)
    heads = (int(hf_cfg.num_attention_heads) if hf_cfg is not None
             else int(overrides["num_heads"]))
    hd = q_rows // heads
    cfg = dict(vocab_size=g("embed_tokens.weight").shape[0],
               num_layers=n_layers, d_model=D, num_heads=heads,
               num_kv_heads=kv_rows // hd,
               d_ff=g("layers.0.block_sparse_moe.experts.0.w1.weight"
                      ).shape[0],
               num_experts=n_experts)
    if hf_cfg is not None:
        sw = getattr(hf_cfg, "sliding_window", None)
        if sw is not None and sw < int(getattr(
                hf_cfg, "max_position_embeddings", sw)):
            raise NotImplementedError(
                f"mixtral_from_hf: checkpoint uses sliding_window={sw}; "
                "the native attention is full-context — converting would "
                "change logits beyond the window")
        cfg["rope_theta"] = float(getattr(hf_cfg, "rope_theta", 1e6))
        cfg["rms_norm_eps"] = float(getattr(hf_cfg, "rms_norm_eps", 1e-5))
        cfg["max_seq_len"] = int(getattr(hf_cfg, "max_position_embeddings",
                                         4096))
        cfg["top_k"] = int(getattr(hf_cfg, "num_experts_per_tok", 2))
    cfg.update(overrides)
    # eval/serving is drop-free by default (MixtralConfig
    # eval_capacity_factor=None), matching HF's capacity-less routing
    model = mixtral_model("custom", **cfg)

    def stack_t(fmt):
        return np.stack([g(fmt.format(i)).T for i in range(n_layers)])

    def stack(fmt):
        return np.stack([g(fmt.format(i)) for i in range(n_layers)])

    def experts_t(w):
        # [L, E, in, out]: per-layer stack of transposed expert mats
        return np.stack([
            np.stack([
                g(f"layers.{i}.block_sparse_moe.experts.{e}.{w}.weight").T
                for e in range(n_experts)])
            for i in range(n_layers)])

    params = {
        "wte": g("embed_tokens.weight"),
        "blocks": {
            "attn_norm": stack("layers.{}.input_layernorm.weight"),
            "wq": stack_t("layers.{}.self_attn.q_proj.weight"),
            "wk": stack_t("layers.{}.self_attn.k_proj.weight"),
            "wv": stack_t("layers.{}.self_attn.v_proj.weight"),
            "wo": stack_t("layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack("layers.{}.post_attention_layernorm.weight"),
            "moe": {
                "router": stack_t("layers.{}.block_sparse_moe.gate.weight"),
                "w_gate": experts_t("w1"),
                "w_in": experts_t("w3"),
                "w_out": experts_t("w2"),
            },
        },
        "final_norm": g("norm.weight"),
        "lm_head": _to_np(sd["lm_head.weight"]).T
        if "lm_head.weight" in sd else g("embed_tokens.weight").T,
    }
    return model, params


def opt_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF OPTForCausalLM (or its state_dict) -> (Model, params).

    OPT is a pre-LN GPT-2-family decoder with ReLU MLPs and learned
    positions stored at a +2 offset (OPTLearnedPositionalEmbedding); the
    offset rows are sliced away so native arange positions line up."""
    from deepspeed_tpu.models.gpt2 import gpt2_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"model.decoder.{k}"])
    n_layers = 1 + max(int(k.split(".")[3]) for k in sd
                       if k.startswith("model.decoder.layers."))
    hf_cfg = getattr(model_or_sd, "config", None)
    D = g("embed_tokens.weight").shape[1]
    if hf_cfg is not None:
        if not getattr(hf_cfg, "do_layer_norm_before", True):
            raise NotImplementedError(
                "opt_from_hf: do_layer_norm_before=False (the 350m post-LN "
                "variant) is not representable by the pre-LN native block")
        if int(getattr(hf_cfg, "word_embed_proj_dim", D)) != D:
            raise NotImplementedError(
                "opt_from_hf: word_embed_proj_dim != hidden_size "
                "(projection in/out layers) is not representable")
    if hf_cfg is None and "num_heads" not in overrides:
        # head_dim varies across the OPT family (80 at 2.7b): never guess
        raise ValueError(
            "opt_from_hf: bare state_dict carries no config — pass the "
            "transformers model or a num_heads= override")
    # an activation override names the HF form; consume it here (through
    # the same map) so cfg.update below cannot clobber the translation
    act = (str(overrides.pop("activation"))
           if "activation" in overrides
           else str(getattr(hf_cfg, "activation_function", "relu"))
           if hf_cfg is not None else "relu")
    # HF "gelu" is the exact erf form; gelu_new is the tanh approximation
    act_map = {"relu": "relu", "gelu": "gelu_exact", "gelu_new": "gelu"}
    if act not in act_map:
        raise NotImplementedError(
            f"opt_from_hf: activation_function={act!r} is not representable "
            "(relu/gelu only)")
    wpe = g("embed_positions.weight")
    ffn = _to_np(sd["model.decoder.layers.0.fc1.weight"]).shape[0]
    cfg = dict(vocab_size=g("embed_tokens.weight").shape[0],
               max_seq_len=wpe.shape[0] - 2,       # drop the +2 offset rows
               num_layers=n_layers, d_model=D,
               num_heads=(int(hf_cfg.num_attention_heads)
                          if hf_cfg is not None else overrides["num_heads"]),
               activation=act_map[act], mlp_dim=ffn)
    cfg.update(overrides)
    model = gpt2_model("custom", **cfg)
    if "lm_head.weight" in sd and not np.allclose(
            _to_np(sd["lm_head.weight"]), g("embed_tokens.weight")):
        raise ValueError(
            "opt_from_hf: checkpoint has an UNTIED lm_head; the native "
            "gpt2-family block ties the head to the embedding")

    def lay(i, k):
        return _to_np(sd[f"model.decoder.layers.{i}.{k}"])

    def stack(k, transpose=False):
        return np.stack([lay(i, k).T if transpose else lay(i, k)
                         for i in range(n_layers)])

    qkv_w = np.concatenate([stack("self_attn.q_proj.weight", True),
                            stack("self_attn.k_proj.weight", True),
                            stack("self_attn.v_proj.weight", True)], axis=-1)
    qkv_b = np.concatenate([stack("self_attn.q_proj.bias"),
                            stack("self_attn.k_proj.bias"),
                            stack("self_attn.v_proj.bias")], axis=-1)
    params = {
        "wte": g("embed_tokens.weight"),
        "wpe": wpe[2:],
        "blocks": {
            "ln1_scale": stack("self_attn_layer_norm.weight"),
            "ln1_bias": stack("self_attn_layer_norm.bias"),
            "qkv_w": qkv_w, "qkv_b": qkv_b,
            "proj_w": stack("self_attn.out_proj.weight", True),
            "proj_b": stack("self_attn.out_proj.bias"),
            "ln2_scale": stack("final_layer_norm.weight"),
            "ln2_bias": stack("final_layer_norm.bias"),
            "mlp_in_w": stack("fc1.weight", True),
            "mlp_in_b": stack("fc1.bias"),
            "mlp_out_w": stack("fc2.weight", True),
            "mlp_out_b": stack("fc2.bias"),
        },
        "lnf_scale": g("final_layer_norm.weight"),
        "lnf_bias": g("final_layer_norm.bias"),
    }
    return model, params


def neox_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF GPTNeoXForCausalLM (or its state_dict) -> (Model, params).

    The fused query_key_value weight is head-major [H, 3, hd, D]; its
    transpose [D, H*(3*hd)] already matches the native per-head
    [q|k|v] packing, so no de-interleave is needed."""
    from deepspeed_tpu.models.neox import neox_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"gpt_neox.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("gpt_neox.layers."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None and "num_heads" not in overrides:
        raise ValueError(
            "neox_from_hf: bare state_dict carries no config — pass the "
            "transformers model or a num_heads= override (rotary_pct= and "
            "rope_theta= too if not 0.25/10000)")
    D = g("embed_in.weight").shape[1]
    cfg = dict(vocab_size=g("embed_in.weight").shape[0],
               num_layers=n_layers, d_model=D)
    if hf_cfg is not None:
        cfg["num_heads"] = int(hf_cfg.num_attention_heads)
        cfg["rotary_pct"] = float(getattr(hf_cfg, "rotary_pct", 0.25))
        cfg["rope_theta"] = float(getattr(hf_cfg, "rotary_emb_base", 10000))
        cfg["layer_norm_eps"] = float(getattr(hf_cfg, "layer_norm_eps",
                                              1e-5))
        cfg["max_seq_len"] = int(getattr(hf_cfg, "max_position_embeddings",
                                         2048))
        cfg["use_parallel_residual"] = bool(
            getattr(hf_cfg, "use_parallel_residual", True))
        act = str(getattr(hf_cfg, "hidden_act", "gelu"))
        approx = {"gelu": False, "gelu_new": True, "gelu_fast": True,
                  "gelu_pytorch_tanh": True}
        if act not in approx:
            raise NotImplementedError(
                f"neox_from_hf: hidden_act={act!r} is not representable")
        cfg["gelu_approximate"] = approx[act]
    cfg.update(overrides)
    model = neox_model("custom", **cfg)

    def stack(fmt, transpose=False):
        return np.stack([_to_np(sd[f"gpt_neox.layers.{i}.{fmt}"]).T
                         if transpose else
                         _to_np(sd[f"gpt_neox.layers.{i}.{fmt}"])
                         for i in range(n_layers)])

    params = {
        "wte": g("embed_in.weight"),
        "blocks": {
            "ln1_scale": stack("input_layernorm.weight"),
            "ln1_bias": stack("input_layernorm.bias"),
            "ln2_scale": stack("post_attention_layernorm.weight"),
            "ln2_bias": stack("post_attention_layernorm.bias"),
            "qkv_w": stack("attention.query_key_value.weight", True),
            "qkv_b": stack("attention.query_key_value.bias"),
            "dense_w": stack("attention.dense.weight", True),
            "dense_b": stack("attention.dense.bias"),
            "mlp_in_w": stack("mlp.dense_h_to_4h.weight", True),
            "mlp_in_b": stack("mlp.dense_h_to_4h.bias"),
            "mlp_out_w": stack("mlp.dense_4h_to_h.weight", True),
            "mlp_out_b": stack("mlp.dense_4h_to_h.bias"),
        },
        "lnf_scale": g("final_layer_norm.weight"),
        "lnf_bias": g("final_layer_norm.bias"),
        "embed_out": _to_np(sd["embed_out.weight"]).T,
    }
    return model, params


def megatron_gpt_from_sd(state_dict, num_heads: int,
                         **overrides) -> Tuple[Any, dict]:
    """Megatron-LM GPT state dict -> (Model, params) (reference container:
    module_inject/containers/megatron_gpt.py:1 + policy megatron_v2).

    Classic Megatron GPT is the pre-LN GPT-2 block with learned positions
    and a tied head; the one wire difference from HF GPT-2 is the fused
    ``attention.query_key_value`` packing: torch-Linear rows ordered
    HEAD-MAJOR ``[H, 3, hd]`` (each head's q,k,v contiguous) where the
    native gpt2 layout is thirds ``[q_all | k_all | v_all]`` — the
    converter de-interleaves.  Keys are accepted with or without the
    ``model./language_model.`` prefixes and with ``transformer.`` or
    ``encoder.`` as the layer container (old/new Megatron-LM)."""
    from deepspeed_tpu.models.gpt2 import gpt2_model

    sd = {}
    for key, val in _state_dict(state_dict).items():
        for pre in ("model.", "language_model."):
            if key.startswith(pre):
                key = key[len(pre):]
        if key.startswith("encoder."):
            key = key.replace("encoder.", "transformer.", 1)
        # new Megatron-LM names the attention module self_attention
        key = key.replace(".self_attention.", ".attention.")
        sd[key] = val
    g = lambda k: _to_np(sd[k])
    n_layers = 1 + max(
        int(k.split(".")[2]) for k in sd
        if k.startswith("transformer.layers."))
    wte = g("embedding.word_embeddings.weight")
    wpe = g("embedding.position_embeddings.weight")
    V, D = wte.shape
    H = int(num_heads)
    hd = D // H
    ffn = _to_np(
        sd["transformer.layers.0.mlp.dense_h_to_4h.weight"]).shape[0]
    cfg = dict(vocab_size=V, max_seq_len=wpe.shape[0], num_layers=n_layers,
               d_model=D, num_heads=H, activation="gelu", mlp_dim=ffn)
    cfg.update(overrides)
    model = gpt2_model("custom", **cfg)

    def lay(i, k):
        return _to_np(sd[f"transformer.layers.{i}.{k}"])

    def stack(fmt, transpose=False):
        return np.stack([lay(i, fmt).T if transpose else lay(i, fmt)
                         for i in range(n_layers)])

    # head-major [H, 3, hd] rows -> native thirds [q|k|v] columns
    def deinterleave_w(fmt):
        return np.stack([
            lay(i, fmt).reshape(H, 3, hd, D)
            .transpose(3, 1, 0, 2).reshape(D, 3 * D)
            for i in range(n_layers)])

    def deinterleave_b(fmt):
        return np.stack([
            lay(i, fmt).reshape(H, 3, hd)
            .transpose(1, 0, 2).reshape(3 * D)
            for i in range(n_layers)])

    params = {
        "wte": wte,
        "wpe": wpe,
        "blocks": {
            "ln1_scale": stack("input_layernorm.weight"),
            "ln1_bias": stack("input_layernorm.bias"),
            "qkv_w": deinterleave_w("attention.query_key_value.weight"),
            "qkv_b": deinterleave_b("attention.query_key_value.bias"),
            "proj_w": stack("attention.dense.weight", True),
            "proj_b": stack("attention.dense.bias"),
            "ln2_scale": stack("post_attention_layernorm.weight"),
            "ln2_bias": stack("post_attention_layernorm.bias"),
            "mlp_in_w": stack("mlp.dense_h_to_4h.weight", True),
            "mlp_in_b": stack("mlp.dense_h_to_4h.bias"),
            "mlp_out_w": stack("mlp.dense_4h_to_h.weight", True),
            "mlp_out_b": stack("mlp.dense_4h_to_h.bias"),
        },
        "lnf_scale": g("transformer.final_layernorm.weight"),
        "lnf_bias": g("transformer.final_layernorm.bias"),
    }
    return model, params


def distilbert_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF DistilBertForMaskedLM (or its state_dict) -> (Model, params)
    (reference container: module_inject/containers/distil_bert.py:1).

    DistilBERT is the BERT post-LN block without token-type embeddings:
    the native bert model carries it with ``type_vocab_size=1`` and a
    zero type row (the no-token_type_ids path adds row 0).  The MLM head
    (vocab_transform -> gelu -> vocab_layer_norm -> tied projector +
    bias) matches the native head shape exactly."""
    from deepspeed_tpu.models.bert import bert_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"distilbert.{k}"])
    n_layers = 1 + max(int(k.split(".")[3]) for k in sd
                       if k.startswith("distilbert.transformer.layer."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is not None and getattr(hf_cfg, "sinusoidal_pos_embds",
                                      False):
        raise NotImplementedError(
            "distilbert_from_hf: sinusoidal_pos_embds is not representable "
            "(native model stores learned positions)")
    D = g("embeddings.word_embeddings.weight").shape[1]
    V = g("embeddings.word_embeddings.weight").shape[0]
    M = _to_np(sd["distilbert.transformer.layer.0.ffn.lin1.weight"]).shape[0]
    if M != 4 * D:
        raise NotImplementedError(
            f"distilbert_from_hf: hidden_dim {M} != 4*dim {4 * D} is not "
            "representable (native bert block fixes d_mlp = 4*d_model)")
    cfg = dict(
        vocab_size=V,
        max_seq_len=g("embeddings.position_embeddings.weight").shape[0],
        type_vocab_size=1, num_layers=n_layers, d_model=D,
        num_heads=(int(hf_cfg.n_heads) if hf_cfg is not None
                   else max(1, D // 64)),
        gelu_approximate=(
            str(getattr(hf_cfg, "activation", "gelu"))
            in ("gelu_new", "gelu_pytorch_tanh", "gelu_fast")
            if hf_cfg is not None else False))
    cfg.update(overrides)
    model = bert_model("custom", **cfg)
    if not np.allclose(_to_np(sd["vocab_projector.weight"]),
                       g("embeddings.word_embeddings.weight")):
        raise ValueError(
            "distilbert_from_hf: checkpoint has an UNTIED vocab_projector; "
            "the native MLM head ties the decoder to the embedding")

    def lay(i, k):
        return _to_np(sd[f"distilbert.transformer.layer.{i}.{k}"])

    def stack(fmt, transpose=False):
        return np.stack([lay(i, fmt).T if transpose else lay(i, fmt)
                         for i in range(n_layers)])

    qkv_w = np.concatenate([stack("attention.q_lin.weight", True),
                            stack("attention.k_lin.weight", True),
                            stack("attention.v_lin.weight", True)], axis=-1)
    qkv_b = np.concatenate([stack("attention.q_lin.bias"),
                            stack("attention.k_lin.bias"),
                            stack("attention.v_lin.bias")], axis=-1)
    params = {
        "wte": g("embeddings.word_embeddings.weight"),
        "wpe": g("embeddings.position_embeddings.weight"),
        "wtype": np.zeros((1, D), np.float32),
        "emb_ln_scale": g("embeddings.LayerNorm.weight"),
        "emb_ln_bias": g("embeddings.LayerNorm.bias"),
        "blocks": {
            "qkv_w": qkv_w, "qkv_b": qkv_b,
            "proj_w": stack("attention.out_lin.weight", True),
            "proj_b": stack("attention.out_lin.bias"),
            "ln1_scale": stack("sa_layer_norm.weight"),
            "ln1_bias": stack("sa_layer_norm.bias"),
            "mlp_in_w": stack("ffn.lin1.weight", True),
            "mlp_in_b": stack("ffn.lin1.bias"),
            "mlp_out_w": stack("ffn.lin2.weight", True),
            "mlp_out_b": stack("ffn.lin2.bias"),
            "ln2_scale": stack("output_layer_norm.weight"),
            "ln2_bias": stack("output_layer_norm.bias"),
        },
        "mlm_dense_w": _to_np(sd["vocab_transform.weight"]).T,
        "mlm_dense_b": _to_np(sd["vocab_transform.bias"]),
        "mlm_ln_scale": _to_np(sd["vocab_layer_norm.weight"]),
        "mlm_ln_bias": _to_np(sd["vocab_layer_norm.bias"]),
        "mlm_bias": _to_np(sd["vocab_projector.bias"]),
    }
    return model, params


def gptj_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF GPTJForCausalLM (or its state_dict) -> (Model, params)
    (reference container: module_inject/containers/gptj.py:1).

    GPT-J maps onto the native NeoX block: parallel residual, partial
    rotary (``rotary_dim`` of each head) with the rotate-every-two
    pairing (``rotary_interleaved``), a SINGLE shared block LayerNorm
    (converted as ln2 := ln1), bias-free attention projections (zeros),
    and a biased untied lm_head (``head_bias``)."""
    from deepspeed_tpu.models.neox import neox_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"transformer.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("transformer.h."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None and ("num_heads" not in overrides
                           or "rotary_pct" not in overrides):
        raise ValueError(
            "gptj_from_hf: bare state_dict carries no config — pass the "
            "transformers model or num_heads= and rotary_pct= "
            "(rotary_dim/head_dim) overrides")
    D = g("wte.weight").shape[1]
    cfg = dict(vocab_size=g("wte.weight").shape[0],
               num_layers=n_layers, d_model=D,
               use_parallel_residual=True, rotary_interleaved=True,
               head_bias=True)
    if hf_cfg is not None:
        H = int(hf_cfg.n_head)
        hd = D // H
        cfg["num_heads"] = H
        cfg["rotary_pct"] = float(getattr(hf_cfg, "rotary_dim", hd) or hd) / hd
        cfg["max_seq_len"] = int(getattr(hf_cfg, "n_positions", 2048))
        cfg["layer_norm_eps"] = float(getattr(hf_cfg, "layer_norm_epsilon",
                                              1e-5))
        act = str(getattr(hf_cfg, "activation_function", "gelu_new"))
        approx = {"gelu": False, "gelu_new": True, "gelu_fast": True,
                  "gelu_pytorch_tanh": True}
        if act not in approx:
            raise NotImplementedError(
                f"gptj_from_hf: activation_function={act!r} is not "
                "representable")
        cfg["gelu_approximate"] = approx[act]
    cfg.update(overrides)
    model = neox_model("custom", **cfg)
    H = cfg["num_heads"]
    hd = D // H

    def lay(i, k):
        return _to_np(sd[f"transformer.h.{i}.{k}"])

    def stack(fmt, transpose=False):
        return np.stack([lay(i, fmt).T if transpose else lay(i, fmt)
                         for i in range(n_layers)])

    # head-major [q|k|v] packing per head (the NeoX fused-QKV layout):
    # [L, D, H, hd] per projection, concatenated on the last axis
    def hm(fmt):
        return stack(fmt, True).reshape(n_layers, D, H, hd)

    qkv_w = np.concatenate([hm("attn.q_proj.weight"),
                            hm("attn.k_proj.weight"),
                            hm("attn.v_proj.weight")],
                           axis=-1).reshape(n_layers, D, 3 * D)
    ln_w = stack("ln_1.weight")
    ln_b = stack("ln_1.bias")
    params = {
        "wte": g("wte.weight"),
        "blocks": {
            # GPT-J's one shared LayerNorm feeds both branches
            "ln1_scale": ln_w, "ln1_bias": ln_b,
            "ln2_scale": ln_w.copy(), "ln2_bias": ln_b.copy(),
            "qkv_w": qkv_w,
            "qkv_b": np.zeros((n_layers, 3 * D), np.float32),
            "dense_w": stack("attn.out_proj.weight", True),
            "dense_b": np.zeros((n_layers, D), np.float32),
            "mlp_in_w": stack("mlp.fc_in.weight", True),
            "mlp_in_b": stack("mlp.fc_in.bias"),
            "mlp_out_w": stack("mlp.fc_out.weight", True),
            "mlp_out_b": stack("mlp.fc_out.bias"),
        },
        "lnf_scale": g("ln_f.weight"), "lnf_bias": g("ln_f.bias"),
        "embed_out": _to_np(sd["lm_head.weight"]).T,
        "embed_out_b": _to_np(sd["lm_head.bias"]),
    }
    return model, params


def gptneo_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF GPTNeoForCausalLM (or its state_dict) -> (Model, params)
    (reference container: module_inject/containers/gptneo.py:1).

    GPT-2 layout with bias-free separate q/k/v projections (zero-filled
    into the fused qkv bias), alternating global/local attention expanded
    from ``attention_types``, and unscaled scores — all carried by the
    native gptneo model."""
    from deepspeed_tpu.models.gptneo import gptneo_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"transformer.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("transformer.h."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None and "num_heads" not in overrides:
        raise ValueError(
            "gptneo_from_hf: bare state_dict carries no config — pass the "
            "transformers model or a num_heads= override (and "
            "attention_layers= if not the alternating default)")
    D = g("wte.weight").shape[1]
    wpe = g("wpe.weight")
    cfg = dict(vocab_size=g("wte.weight").shape[0],
               max_seq_len=wpe.shape[0], num_layers=n_layers, d_model=D)
    if hf_cfg is not None:
        cfg["num_heads"] = int(hf_cfg.num_heads)
        cfg["window_size"] = int(getattr(hf_cfg, "window_size", 256))
        cfg["attention_layers"] = tuple(hf_cfg.attention_layers)
        cfg["layer_norm_eps"] = float(getattr(hf_cfg, "layer_norm_epsilon",
                                              1e-5))
        act = str(getattr(hf_cfg, "activation_function", "gelu_new"))
        act_map = {"relu": "relu", "gelu": "gelu_exact", "gelu_new": "gelu",
                   "gelu_pytorch_tanh": "gelu"}
        if act not in act_map:
            raise NotImplementedError(
                f"gptneo_from_hf: activation_function={act!r} is not "
                "representable")
        cfg["activation"] = act_map[act]
        inter = getattr(hf_cfg, "intermediate_size", None)
        if inter:
            cfg["mlp_dim"] = int(inter)
    cfg.update(overrides)
    model = gptneo_model("custom", **cfg)
    if "lm_head.weight" in sd and not np.allclose(
            _to_np(sd["lm_head.weight"]), g("wte.weight")):
        raise ValueError(
            "gptneo_from_hf: checkpoint has an UNTIED lm_head; the native "
            "gpt2-family block ties the head to the embedding")

    def stack(fmt, transpose=False):
        return np.stack([_to_np(sd[f"transformer.h.{i}.{fmt}"]).T
                         if transpose else
                         _to_np(sd[f"transformer.h.{i}.{fmt}"])
                         for i in range(n_layers)])

    qkv_w = np.concatenate([stack("attn.attention.q_proj.weight", True),
                            stack("attn.attention.k_proj.weight", True),
                            stack("attn.attention.v_proj.weight", True)],
                           axis=-1)
    params = {
        "wte": g("wte.weight"),
        "wpe": wpe,
        "blocks": {
            "ln1_scale": stack("ln_1.weight"),
            "ln1_bias": stack("ln_1.bias"),
            "qkv_w": qkv_w,
            "qkv_b": np.zeros((n_layers, 3 * D), np.float32),
            "proj_w": stack("attn.attention.out_proj.weight", True),
            "proj_b": stack("attn.attention.out_proj.bias"),
            "ln2_scale": stack("ln_2.weight"),
            "ln2_bias": stack("ln_2.bias"),
            "mlp_in_w": stack("mlp.c_fc.weight", True),
            "mlp_in_b": stack("mlp.c_fc.bias"),
            "mlp_out_w": stack("mlp.c_proj.weight", True),
            "mlp_out_b": stack("mlp.c_proj.bias"),
        },
        "lnf_scale": g("ln_f.weight"), "lnf_bias": g("ln_f.bias"),
    }
    return model, params


def bloom_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF BloomForCausalLM (or its state_dict) -> (Model, params).

    Same head-major fused-QKV layout as NeoX (transpose = native
    packing); ALiBi slopes are recomputed from the head count."""
    from deepspeed_tpu.models.bloom import bloom_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"transformer.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("transformer.h."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None and "num_heads" not in overrides:
        # head_dim varies across the BLOOM family (128 at 7b1): never guess
        raise ValueError(
            "bloom_from_hf: bare state_dict carries no config — pass the "
            "transformers model or a num_heads= override")
    D = g("word_embeddings.weight").shape[1]
    cfg = dict(vocab_size=g("word_embeddings.weight").shape[0],
               num_layers=n_layers, d_model=D,
               num_heads=(int(hf_cfg.n_head) if hf_cfg is not None
                          else overrides["num_heads"]))
    if hf_cfg is not None:
        cfg["layer_norm_eps"] = float(getattr(hf_cfg, "layer_norm_epsilon",
                                              1e-5))
    cfg.update(overrides)
    model = bloom_model("custom", **cfg)

    def stack(fmt, transpose=False):
        return np.stack([_to_np(sd[f"transformer.h.{i}.{fmt}"]).T
                         if transpose else
                         _to_np(sd[f"transformer.h.{i}.{fmt}"])
                         for i in range(n_layers)])

    params = {
        "wte": g("word_embeddings.weight"),
        "emb_ln_scale": g("word_embeddings_layernorm.weight"),
        "emb_ln_bias": g("word_embeddings_layernorm.bias"),
        "blocks": {
            "ln1_scale": stack("input_layernorm.weight"),
            "ln1_bias": stack("input_layernorm.bias"),
            "ln2_scale": stack("post_attention_layernorm.weight"),
            "ln2_bias": stack("post_attention_layernorm.bias"),
            "qkv_w": stack("self_attention.query_key_value.weight", True),
            "qkv_b": stack("self_attention.query_key_value.bias"),
            "dense_w": stack("self_attention.dense.weight", True),
            "dense_b": stack("self_attention.dense.bias"),
            "mlp_in_w": stack("mlp.dense_h_to_4h.weight", True),
            "mlp_in_b": stack("mlp.dense_h_to_4h.bias"),
            "mlp_out_w": stack("mlp.dense_4h_to_h.weight", True),
            "mlp_out_b": stack("mlp.dense_4h_to_h.bias"),
        },
        "lnf_scale": g("ln_f.weight"),
        "lnf_bias": g("ln_f.bias"),
    }
    return model, params
