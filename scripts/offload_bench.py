"""Param-streaming bench: the ISSUE 17 double-buffered layer pipeline.

Measures the NVMe param tier end to end through the SAME policy stack
training uses (ParamStore over SwapEngine): shard write-out bandwidth,
the streamed weight-pass read bandwidth with per-layer host compute
overlapping the next layer's prefetch, and the MEASURED prefetch-overlap
fraction (reads satisfied by an in-flight prefetch vs synchronous
misses) — the quantity the ``offload/param_prefetch_overlap`` gauge
reports in production.

    python scripts/offload_bench.py                    # 12 x 64 MB layers
    PARAM_MB=32 PARAM_N=8 PARAM_K=2 python scripts/offload_bench.py
    DS_BENCH_LEDGER=1 python scripts/offload_bench.py  # append BENCH/ledger

Emits one ds-bench record per run: swap_out/in GB/s, overlap fraction,
pipelined-vs-serialized sweep times, a checksums-on/off A/B (the ISSUE
18 per-payload crc32 cost on both directions), and the memory
observatory's peak bytes (``mem_peak_*``) so ``bench_compare
--history`` gates all three.
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _compute(leaves, ms):
    """Stand-in per-layer compute: touch the shard for ~ms of CPU work
    (a matmul-ish reduction so the bytes really stream through cache)."""
    t0 = time.perf_counter()
    acc = 0.0
    while (time.perf_counter() - t0) * 1e3 < ms:
        acc += float(leaves["w"][:: max(1, leaves["w"].size // 1024)].sum())
    return acc


def main():
    mb = int(os.environ.get("PARAM_MB", 64))
    n = int(os.environ.get("PARAM_N", 12))
    k = int(os.environ.get("PARAM_K", 2))
    compute_ms = float(os.environ.get("PARAM_COMPUTE_MS", 10))
    root = os.environ.get("PARAM_DIR") or tempfile.mkdtemp(prefix="ds_pstream_")

    from deepspeed_tpu.offload import ParamStore, SwapEngine
    from scripts.bench_util import emit_ledger, make_record, mem_peak_fields

    total_gb = n * mb / 1024

    def build(resident, tag="pipe", integrity=None):
        eng = SwapEngine(nvme_dir=os.path.join(root, f"{tag}_k{resident}"),
                         owner="params_nvme", aio_threads=4, queue_depth=2,
                         integrity=integrity)
        store = ParamStore(eng, n, resident_layers=resident)
        rng = np.random.default_rng(0)
        t0 = time.perf_counter()
        for i in range(n):
            store.put_layer(i, {"w": rng.integers(
                0, 255, (mb << 20) // 4, dtype=np.int32).view(np.float32)})
        store.flush()
        return eng, store, time.perf_counter() - t0

    # ---- write-out: every layer shard to NVMe through the write ring
    eng, store, w_s = build(k)

    def sweep(st, direction):
        """One streamed weight pass (forward or backward order)."""
        order = range(n) if direction > 0 else range(n - 1, -1, -1)
        t0 = time.perf_counter()
        for i in order:
            leaves = st.get_layer(i, direction=direction)
            _compute(leaves, compute_ms)
        return time.perf_counter() - t0

    # warm pass fills the K-layer working set; then a forward + backward
    # epoch like the train loop's weight pass (resident copies of the
    # just-used tail satisfy the backward's first reads)
    sweep(store, +1)
    store.resident_hits = store.prefetch_hits = store.sync_misses = 0
    store.fetch_block_s = 0.0
    fetched0 = store.fetch_bytes
    fwd_s = sweep(store, +1)
    bwd_s = sweep(store, -1)
    pipe_s = fwd_s + bwd_s
    read_gb = (store.fetch_bytes - fetched0) / (1 << 30)
    overlap = store.overlap_fraction()
    blocked = store.fetch_block_s

    # ---- serialized baseline: same sweep with prefetch disabled (every
    # read is a synchronous miss) — what the pipeline buys is the delta
    eng2, store2, _ = build(k, tag="serial")
    store2.prefetch_layer = lambda i: None
    sweep(store2, +1)
    store2.fetch_block_s = 0.0
    serial_s = sweep(store2, +1) + sweep(store2, -1)

    # ---- integrity A/B (ISSUE 18): the same write-out + streamed epoch
    # with checksums off — what the per-payload crc32 costs on both
    # directions (the ``resilience.offload.verify_fetch`` knob trades
    # this read-side cost against silent-corruption detection)
    from types import SimpleNamespace
    eng3, store3, w_nc_s = build(
        k, tag="nocrc", integrity=SimpleNamespace(checksums=False))
    sweep(store3, +1)
    store3.fetch_block_s = 0.0
    fetched3 = store3.fetch_bytes
    nocrc_s = sweep(store3, +1) + sweep(store3, -1)
    read_nc_gb = (store3.fetch_bytes - fetched3) / (1 << 30)

    import multiprocessing
    cores = multiprocessing.cpu_count()
    detail = {
        "layer_mb": mb, "layers": n, "resident_layers": k,
        "compute_ms_per_layer": compute_ms,
        "backend": eng._rings()[0].backend(),
        "swap_out_GBps": round(total_gb / w_s, 2),
        "swap_in_GBps": round(read_gb / pipe_s, 2) if pipe_s else 0.0,
        "prefetch_overlap_fraction": round(overlap, 3),
        "fetch_blocked_s": round(blocked, 3),
        "sweep_pipelined_s": round(pipe_s, 3),
        "sweep_serialized_s": round(serial_s, 3),
        "pipeline_speedup": round(serial_s / pipe_s, 2) if pipe_s else 0.0,
        "swap_out_GBps_nocrc": round(total_gb / w_nc_s, 2) if w_nc_s else 0.0,
        "swap_in_GBps_nocrc": round(read_nc_gb / nocrc_s, 2)
        if nocrc_s else 0.0,
        "checksum_write_overhead_pct": round(100 * (w_s - w_nc_s) / w_nc_s, 1)
        if w_nc_s else 0.0,
        "checksum_read_overhead_pct": round(100 * (pipe_s - nocrc_s)
                                            / nocrc_s, 1)
        if nocrc_s else 0.0,
        "cores": cores,
        "dir": root,
    }
    detail.update(mem_peak_fields())
    rec = make_record("param_stream_overlap", round(overlap, 3),
                      unit="fraction", direction="higher_better",
                      detail=detail)
    print(json.dumps(emit_ledger(rec)))
    eng.close()
    eng2.close()
    eng3.close()


if __name__ == "__main__":
    main()
