"""Built-in checkers.  Importing this package registers every rule;
adding a checker = dropping a module here that imports ``register``
from ``..core`` and decorates a ``Checker`` subclass."""
from . import donation      # noqa: F401  DSL001
from . import locks         # noqa: F401  DSL002
from . import jit_hygiene   # noqa: F401  DSL003
from . import registries    # noqa: F401  DSL004
from . import resilience    # noqa: F401  DSL005
