"""ds_ggemm block-shape sweep (ISSUE 8 satellite) — the qgemm_sweep
playbook applied to the grouped expert GEMM: on-chip A/B over TPU-legal
(bm, bk, bn) tile shapes at MoE-relevant grouped shapes (prefill-scale
token counts routed over E experts, K/N = the model's expert FFN dims),
slope-timed per the PERF.md tunnel discipline (on-device fori_loop
chains; only slopes between step counts are trustworthy — a blocking
round trip costs ~100 ms).

    python scripts/ggemm_sweep.py                      # mixtral-8x7B dims
    GGEMM_T=4096 GGEMM_E=8 GGEMM_SHAPES=4096x14336 python scripts/ggemm_sweep.py
    GGEMM_SWEEP_SMOKE=1 python scripts/ggemm_sweep.py  # CPU plumbing smoke

Per (shape, blocks) prints one JSON line each for the float and the
fused-dequant int8 grouped kernel (per-call slope µs + achieved expert
weight-stream GB/s), then the winner per shape; the winning tuple is
what ``DS_GGEMM_BLOCKS=bm,bk,bn`` pins.  The decode-regime slot kernel
(ops/pallas/grouped_gemm.py ds_ggemm_slots) has no M-tiling to sweep —
its row block is the padded batch — so it gets one reference row per
shape at the default (bk, bn).  Off-TPU (smoke) everything runs tiny
interpret-mode shapes — plumbing only, no timing claims.
"""
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


from scripts.bench_util import timed_chain


def main():
    from deepspeed_tpu.ops.pallas import grouped_gemm as gg
    from deepspeed_tpu.ops.pallas.quantization import block_quantize_int8

    smoke = bool(int(os.environ.get("GGEMM_SWEEP_SMOKE", "0")))
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    if smoke or not on_tpu:
        shapes = [(64, 128)]
        T, E, top_k = 24, 4, 2
        grid = [(8, 64, 128)]
        steps = 2
        interpret = True
        dtype = jnp.float32
        decode_rows = 4
    else:
        # mixtral-8x7B expert FFN GEMMs by default: in [4096, 14336],
        # out [14336, 4096]
        env = os.environ.get("GGEMM_SHAPES", "4096x14336,14336x4096")
        shapes = [tuple(int(v) for v in s.split("x"))
                  for s in env.split(",")]
        T = int(os.environ.get("GGEMM_T", 4096))
        E = int(os.environ.get("GGEMM_E", 8))
        top_k = int(os.environ.get("GGEMM_TOPK", 2))
        bms = [128, 256, 512]
        bks = [256, 512, 1024]
        bns = [256, 512, 1024, 2048]
        grid = list(itertools.product(bms, bks, bns))
        steps = int(os.environ.get("GGEMM_STEPS", 20))
        interpret = False
        dtype = jnp.bfloat16
        decode_rows = int(os.environ.get("GGEMM_DECODE_B", 8)) * top_k

    rng = np.random.default_rng(0)
    R = T * top_k
    eids = jnp.asarray(rng.integers(0, E, (R,)), jnp.int32)
    for (K, N) in shapes:
        w = jnp.asarray(rng.standard_normal((E, K, N)), jnp.float32)
        q, s = block_quantize_int8(w)
        w = w.astype(dtype)
        rows = jnp.asarray(rng.standard_normal((R, K)), dtype)
        best = {}                   # per kind: float and int8 tilings
        #                             can differ (the int8 kernel adds
        #                             the per-tile scale expansion)
        for bm, bk, bn in grid:
            plan = gg.make_group_plan(eids, E, block_m=bm)
            x0 = gg.scatter_to_groups(rows, plan)

            def step(state, _w=None, _bk=bk, _bn=bn, _plan=plan):
                x, acc = state
                y = gg.ds_ggemm(x, _w, _plan, block_k=_bk, block_n=_bn,
                                interpret=interpret)
                # data dependency so the chain cannot be elided
                carry = x + jnp.tanh(y[:, :1]).astype(x.dtype)
                return (carry, acc + jnp.sum(y).astype(jnp.float32))

            for tag, wt, wbytes in (
                    ("f", w, int(w.size) * w.dtype.itemsize),
                    ("int8", (q, s), int(q.size) + 4 * int(s.size))):
                try:
                    sec = max(timed_chain(
                        lambda st, _wt=wt, _bk=bk, _bn=bn, _plan=plan:
                        step(st, _wt, _bk, _bn, _plan),
                        (x0, jnp.float32(0)), steps), 0.0)
                except Exception as e:  # keep sweeping past illegal tilings
                    print(json.dumps({"shape": f"{K}x{N}", "kind": tag,
                                      "blocks": [bm, bk, bn],
                                      "error": str(e)[:200]}))
                    continue
                gbs = wbytes / sec / 1e9 if sec > 0 else None
                row = {"shape": f"{K}x{N}", "kind": tag, "tokens": T,
                       "experts": E, "top_k": top_k,
                       "blocks": [bm, bk, bn],
                       "us_per_call": round(sec * 1e6, 2),
                       "weight_stream_GBs": round(gbs, 1) if gbs else None}
                print(json.dumps(row))
                if sec > 0 and (tag not in best or sec < best[tag][0]):
                    best[tag] = (sec, row)
        for tag, (sec_w, row) in sorted(best.items()):
            print(json.dumps({"shape": f"{K}x{N}", "kind": tag,
                              "winner": row}))
            from scripts.bench_util import emit_ledger
            emit_ledger({"metric": f"ggemm_sweep_{tag}_{K}x{N}",
                         "value": round(sec_w * 1e6, 2),
                         "unit": "us_per_call",
                         "direction": "lower_better",
                         "detail": {"blocks": str(row["blocks"])}})

        # decode-regime slot kernel: one row per shape (no M sweep — the
        # row block is the padded batch; bk/bn ride the defaults)
        d_eids = jnp.asarray(rng.integers(0, E, (decode_rows,)), jnp.int32)
        d_rows = jnp.asarray(rng.standard_normal((decode_rows, K)), dtype)
        splan = gg.make_slot_plan(d_eids, E)

        def slot_step(state):
            x, acc = state
            y = gg.ds_ggemm_slots(x, (q, s), splan, interpret=interpret)
            carry = x + jnp.tanh(y[:, :1]).astype(x.dtype)
            return (carry, acc + jnp.sum(y).astype(jnp.float32))

        try:
            sec = max(timed_chain(slot_step, (d_rows, jnp.float32(0)),
                                  steps), 0.0)
            distinct = min(decode_rows, E)
            sbytes = (int(q.size) + 4 * int(s.size)) * distinct // E
            print(json.dumps({
                "shape": f"{K}x{N}", "kind": "int8_slots",
                "rows": decode_rows, "distinct_experts_bound": distinct,
                "us_per_call": round(sec * 1e6, 2),
                "weight_stream_GBs": (round(sbytes / sec / 1e9, 1)
                                      if sec > 0 else None)}))
        except Exception as e:
            print(json.dumps({"shape": f"{K}x{N}", "kind": "int8_slots",
                              "error": str(e)[:200]}))


if __name__ == "__main__":
    main()
